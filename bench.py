"""Benchmark: DLRM (Criteo shape) training throughput on the real TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config mirrors the Criteo-DLRM shape (BASELINE.json): 13 dense features,
26 categorical slots (dim 16, vocab 1M each), batch 4096.

Default mode = the TPU-native fused path: all 26 tables resident in HBM,
the whole hybrid step (gather → DLRM fwd/bwd → optax dense update →
duplicate-safe sparse Adagrad) is ONE jitted XLA program
(persia_tpu/parallel/fused_step.py). Host↔device traffic per step is just
the raw batch: one int32 id buffer + one f32 dense/label buffer in; loss
stays on device and is fetched once at the end. This is the idiomatic TPU
answer to the reference's async CPU-PS pipeline for tables that fit in HBM;
the C++ host-PS tier (BENCH_MODE=hybrid) remains the capacity tier for
beyond-HBM vocab (reference's 100T regime, README.md:29).

``vs_baseline`` divides measured samples/sec by REF_SAMPLES_PER_SEC, the
derived per-A100 DLRM training throughput (BASELINE.md shows the
arithmetic; the reference repo publishes no absolute numbers). ``mfu`` is
model-FLOPs utilization: dense-model train FLOPs/sample (computed below
from the bench shape) x samples/sec / the chip's bf16 peak — DLRM is
embedding/wire-bound, so single-digit MFU is the honest, expected number
(the FLOPs are in the MLPs; the work is in the gathers and the wires).
"""

import json
import os
import time

import numpy as np

# Derived per-A100 anchor (see BASELINE.md "Per-A100 baseline"): public
# HugeCTR/MLPerf-class DLRM training lands ~3.5M samples/s on a DGX-A100
# (8xA100) => ~440k per A100; rounded UP to 500k as a generous anchor.
REF_SAMPLES_PER_SEC = 500_000.0

BATCH_SIZE = 4096
N_DENSE = 13
N_SLOTS = 26
EMB_DIM = 16
VOCAB = 1_000_000
WARMUP_STEPS = 5
MEASURE_STEPS = 200

# TPU v5e (this bench's chip) peak dense bf16 throughput.
V5E_PEAK_FLOPS = 197e12


class _Progress:
    """Per-mode partial-result reporter: a ``{"bench_progress": ...}`` JSON
    line every ``every`` steps, so a mode killed by the per-mode wall-clock
    budget (or a degraded link) still yields a labeled datapoint instead of
    rc=1/silence (VERDICT r04: ps-stream produced nothing in 25 min)."""

    def __init__(self, every: int = 25):
        self.every = every
        self.t0 = None
        self.n = 0

    def start(self):
        self.t0 = time.perf_counter()

    def tick(self):
        self.n += 1
        if self.t0 is not None and self.n % self.every == 0:
            el = time.perf_counter() - self.t0
            print(json.dumps({"bench_progress": {
                "steps": self.n,
                "samples_per_sec": round(self.n * BATCH_SIZE / el, 1),
            }}), flush=True)

    def wrap(self, batches):
        """Count batches as the stream's feeder consumes them — runs ahead
        of device execution by <= the prefetch depth, so partial numbers
        from these lines slightly overestimate; the ``partial`` label in the
        final record says so."""
        for b in batches:
            yield b
            self.tick()


def _model_train_flops_per_sample() -> float:
    """Dense-model training FLOPs per sample at the bench shape (matmul
    FLOPs, MAC=2; backward ~= 2x forward; embedding gather/update FLOPs
    excluded by the usual model-FLOPs convention).

    bottom MLP 13->256->64->16, interaction einsum over 27 vectors of
    dim 16 (full (27,27) product as executed on the MXU), top MLP
    (16+351)->512->256->1."""
    bottom = 13 * 256 + 256 * 64 + 64 * 16
    n_vec = N_SLOTS + 1
    interact = n_vec * n_vec * EMB_DIM
    top_in = EMB_DIM + n_vec * (n_vec - 1) // 2
    top = top_in * 512 + 512 * 256 + 256 * 1
    fwd = 2 * (bottom + interact + top)
    return 3.0 * fwd  # fwd + ~2x fwd backward


def bench_fused():
    import jax
    import jax.numpy as jnp
    import optax

    from persia_tpu.embedding.optim import Adagrad
    from persia_tpu.models import DLRM
    from persia_tpu.parallel.fused_step import (
        FusedSlotSpec,
        build_fused_train_step,
        init_fused_state,
        pack_ids,
        unpack_ids,
    )

    stack = os.environ.get("BENCH_STACK", "1") == "1"
    specs = {f"cat_{i}": FusedSlotSpec(vocab=VOCAB, dim=EMB_DIM) for i in range(N_SLOTS)}
    slot_order = sorted(specs)
    model = DLRM(embedding_dim=EMB_DIM, bottom_mlp=(256, 64, EMB_DIM), top_mlp=(512, 256))
    sparse_cfg = Adagrad(lr=0.05).config
    dense_opt = optax.adam(1e-3)

    rng = np.random.default_rng(0)

    def make_host_batch():
        ids, _ = pack_ids(
            {
                n: rng.integers(0, VOCAB, BATCH_SIZE, dtype=np.int32)
                for n in slot_order
            },
            slot_order,
        )
        densel = np.concatenate(
            [
                rng.normal(size=(BATCH_SIZE, N_DENSE)).astype(np.float32),
                rng.integers(0, 2, (BATCH_SIZE, 1)).astype(np.float32),
            ],
            axis=1,
        )
        return ids, densel

    id_shapes = [(BATCH_SIZE,)] * N_SLOTS

    raw_step = build_fused_train_step(
        model, dense_opt, sparse_cfg, specs, slot_order, jit=False, stack=stack
    )

    def packed_step(state, flat_ids, densel):
        ids = unpack_ids(flat_ids, slot_order, id_shapes)
        batch = {
            "dense": [jax.lax.slice(densel, (0, 0), (BATCH_SIZE, N_DENSE))],
            "labels": [jax.lax.slice(densel, (0, N_DENSE), (BATCH_SIZE, N_DENSE + 1))],
            "ids": ids,
        }
        return raw_step(state, batch)

    step = jax.jit(packed_step, donate_argnums=(0,))
    # BENCH_FUSED_K>1: amortize dispatch overhead across K steps with one
    # jitted multi-step program (the fused-path analogue of the cached
    # stream's dispatch_k; parallel/fused_step.build_fused_multi_step is
    # the library form) — on a remote-attached chip every dispatch pays
    # tunnel latency, so the all-in-HBM ceiling is dispatch-bound too
    K = max(1, int(os.environ.get("BENCH_FUSED_K", "1")))

    def multi_body(state, ids_t, dl_t):
        loss = None
        for ids, dl in zip(ids_t, dl_t):
            state, (loss, _) = packed_step(state, ids, dl)
        return state, loss

    multi = jax.jit(multi_body, donate_argnums=(0,)) if K > 1 else None

    # init on a sample batch
    ids0, dl0 = make_host_batch()
    sample = {
        "dense": [dl0[:, :N_DENSE]],
        "labels": [dl0[:, N_DENSE:]],
        "ids": {
            n: jnp.asarray(ids0.reshape(N_SLOTS, BATCH_SIZE)[i])
            for i, n in enumerate(slot_order)
        },
    }
    state = init_fused_state(
        model, jax.random.PRNGKey(0), specs, sample, dense_opt, sparse_cfg,
        stack=stack,
    )

    host_batches = [make_host_batch() for _ in range(8)]

    def group(i):
        picks = [host_batches[(i + j) % len(host_batches)] for j in range(K)]
        return (
            tuple(jnp.asarray(g[0]) for g in picks),
            tuple(jnp.asarray(g[1]) for g in picks),
        )

    if K > 1:
        for i in range(0, max(WARMUP_STEPS, K), K):
            ids_t, dl_t = group(i)
            state, loss = multi(state, ids_t, dl_t)
        loss.block_until_ready()
        steps_run = ((MEASURE_STEPS + K - 1) // K) * K
        t0 = time.perf_counter()
        for i in range(0, steps_run, K):
            ids_t, dl_t = group(i)
            state, loss = multi(state, ids_t, dl_t)
        loss.block_until_ready()
        elapsed = time.perf_counter() - t0
        return _fused_record(steps_run * BATCH_SIZE / elapsed, k=K)

    for i in range(WARMUP_STEPS):
        ids, dl = host_batches[i % len(host_batches)]
        state, (loss, _) = step(state, jnp.asarray(ids), jnp.asarray(dl))
    loss.block_until_ready()

    t0 = time.perf_counter()
    for i in range(MEASURE_STEPS):
        ids, dl = host_batches[i % len(host_batches)]
        state, (loss, _) = step(state, jnp.asarray(ids), jnp.asarray(dl))
    loss.block_until_ready()
    elapsed = time.perf_counter() - t0
    return _fused_record(MEASURE_STEPS * BATCH_SIZE / elapsed, k=1)


def _fused_record(samples_per_sec: float, k: int) -> dict:
    """The fused-tier mode record: like _stream_record, it carries the
    dense-plane sync fields — "local"/0 by construction (one device, one
    program), but stated explicitly so fused/stream/hybrid rows compare on
    the same vocabulary instead of by omission."""
    return {
        "samples_per_sec": round(samples_per_sec, 1),
        "dispatch_mode": f"fused-k{k}" if k > 1 else "fused",
        "sync_mode": "local",
        "dense_wire_bytes_per_step": 0,
    }


def bench_link():
    """Measure the host↔device link (one ~4 MiB transfer each way + the
    small-fetch round-trip). Runs as its own bench mode/subprocess — the
    d2h permanently degrades the process's dispatch latency, and the
    number contextualizes every wire-bound mode: ps-stream and hybrid are
    physically capped at link_d2h / grad_bytes_per_sample samples/sec, so
    the record of WHAT the link did during the run is part of the result."""
    import jax

    dev = jax.devices()[0]
    add = jax.jit(lambda x, i: x + i)
    a = np.random.default_rng(0).standard_normal(1 << 20, dtype=np.float32)  # 4 MiB
    bufs = [a + np.float32(i) for i in range(4)]
    t0 = time.perf_counter()
    ys = [jax.device_put(b, dev) for b in bufs]
    jax.block_until_ready(ys)
    h2d = 4 * len(bufs) / (time.perf_counter() - t0)
    zs = [add(ys[0], float(i)) for i in range(4)]
    jax.block_until_ready(zs)
    t0 = time.perf_counter()
    for z in zs:
        np.asarray(z)
    d2h = 4 * len(zs) / (time.perf_counter() - t0)
    small = add(ys[0][:256], 1.0)
    small.block_until_ready()
    t0 = time.perf_counter()
    for i in range(5):
        np.asarray(add(ys[0][:256], float(i)))
    rt_ms = (time.perf_counter() - t0) / 5 * 1e3
    return {
        "h2d_MBps": round(h2d, 1),
        "d2h_MBps": round(d2h, 1),
        "small_d2h_roundtrip_ms": round(rt_ms, 1),
        # what chip this record was actually measured on — a CPU-hosted
        # run must not be mistaken for a chip number
        "platform": jax.default_backend(),
    }


def _zipf_ids(rng, n, vocab, offset, a=1.2):
    """Rank-skewed ids (production-like): zipf ranks clipped into [0, vocab).
    ``offset`` is a FIXED per-slot shift so each slot has its own stable hot
    set (stable across batches — that is what a cache can exploit) while
    slots stay decorrelated from each other."""
    raw = rng.zipf(a, n).astype(np.uint64)
    return (raw + np.uint64(offset)) % vocab


def _cached_tier_ctx(ps_all: bool = False):
    """THE bench configuration of the cached/ps tiers, shared by
    bench_cached, bench_ps_stream and the quality gate — the quality
    assertion prices exactly the configuration the throughput headline
    runs, env knobs included (one builder, no copy to drift).

    bf16 eviction + checkout wires (the reference ships f16 wires,
    lib.rs:157-180) halve the host↔device bytes; the in-HBM training math
    and the checkpoint flush stay f32. Touch-gated admission (the
    reference's admit_probability semantics: non-admitted signs read
    zeros, their gradients drop) keeps one-hit-wonder zipf-tail signs out,
    collapsing steady-state evictions to the recurring working set."""
    import optax

    from persia_tpu.config import EmbeddingConfig, SlotConfig
    from persia_tpu.embedding.hbm_cache import CachedTrainCtx
    from persia_tpu.embedding.native_store import create_store
    from persia_tpu.embedding.optim import Adagrad
    from persia_tpu.embedding.worker import EmbeddingWorker
    from persia_tpu.models import DLRM

    cfg = EmbeddingConfig(
        slots_config={f"cat_{i}": SlotConfig(dim=EMB_DIM) for i in range(N_SLOTS)},
        feature_index_prefix_bit=8,
    )
    store = create_store(
        "auto", capacity=1 << 25, num_internal_shards=64,
        optimizer=Adagrad(lr=0.05).config, seed=1,
    )
    # device_pooling: PS-tier slots ship per-DISTINCT rows/gradients across
    # the link (the ps-stream regime is gradient-wire-bound; ~3x fewer d2h
    # bytes at this zipf skew)
    worker = EmbeddingWorker(cfg, [store], num_threads=16, device_pooling=True)
    model = DLRM(embedding_dim=EMB_DIM, bottom_mlp=(256, 64, EMB_DIM), top_mlp=(512, 256))
    kw = dict(
        model=model, dense_optimizer=optax.adam(1e-3),
        embedding_optimizer=Adagrad(lr=0.05), worker=worker,
        embedding_config=cfg,
    )
    if ps_all:
        kw.update(
            cache_rows=8,  # unused: every slot rides the PS path
            ps_slots=[f"cat_{i}" for i in range(N_SLOTS)],
            # int8 error-feedback gradient-return wire by default (~4× vs
            # f32, 2× vs the previous bf16 on the d2h ceiling that caps
            # this regime); quality-gated by the int8-vs-f32 parity test
            # (tests/test_hbm_cache.py) and priced by BENCH_MODE=quality.
            # BENCH_PS_WIRE=bfloat16/float32 restores the wider wires.
            ps_wire_dtype=os.environ.get("BENCH_PS_WIRE", "int8"),
        )
    else:
        kw.update(
            # 2M rows in HBM vs 26M-sign PS vocabulary; shrink via env to
            # reach the post-fill eviction steady state in fewer steps
            cache_rows=int(os.environ.get("BENCH_CACHE_ROWS", str(1 << 21))),
            wb_wire_dtype="bfloat16",
            aux_wire_dtype=os.environ.get("BENCH_AUX_WIRE", "bfloat16"),
            admit_touches=int(os.environ.get("BENCH_ADMIT_TOUCHES", "2")),
        )
    return CachedTrainCtx(**kw).__enter__()


def _dispatch_k() -> int:
    """Multi-step fused dispatch depth for the stream modes (the K-step
    hazard-free packing in hbm_cache/stream.py); BENCH_DISPATCH_K=1
    restores the serial one-step-per-dispatch cadence for A/B runs."""
    return int(os.environ.get("BENCH_DISPATCH_K", "8"))


def _pipeline_depth() -> int:
    """MPMD stage-pipeline depth for the stream modes (pipeline_depth in
    hbm_cache/stream.py: feeds hoist up to depth-1 steps above the dense
    stage under the hazard ledger). Default 1 keeps the historical
    in-order records comparable; the cached-pipelined mode A/Bs both on
    one record."""
    return int(os.environ.get("BENCH_PIPELINE_DEPTH", "1"))


def _stream_record(ctx, samples_per_sec: float) -> dict:
    """The cached-tier mode record: throughput plus the dispatch-mode and
    feeder-utilization fields that make hot-loop regressions visible from
    the committed JSON alone (a saturated number that quietly fell back to
    single-step dispatch, or a feeder pinned at 100%, is a finding)."""
    st = ctx.stream_stats() or {}
    total = st.get("packed_steps", 0) + st.get("single_steps", 0)
    depth = st.get("pipeline_depth", 1)
    if depth > 1:
        dispatch_mode = f"pipe-{depth}-k{st.get('dispatch_k', 1)}"
    elif st.get("dispatch_k", 1) > 1:
        dispatch_mode = f"kstep-{st.get('dispatch_k')}"
    else:
        dispatch_mode = "single"
    rec = {
        "samples_per_sec": round(samples_per_sec, 1),
        "dispatch_mode": dispatch_mode,
        "packed_step_frac": (
            round(st.get("packed_steps", 0) / total, 3) if total else 0.0
        ),
        "packs": st.get("packs", 0),
        "feeder_util": (
            round(st.get("feeder_busy_s", 0.0) / st["wall_s"], 3)
            if st.get("wall_s") else None
        ),
        # resilience accounting: a cached run that trained on degraded
        # (synthetic) lookups must say so in its own record
        "degraded_steps": st.get("degraded_steps", 0),
        "degraded_lookup_frac_max": st.get("degraded_lookup_frac_max", 0.0),
        # tier accounting (auto-tiering observability): where every slot
        # lives at stream end, per-group occupancy, and the cache hit rate
        # — a placement regression shows up here before it shows up in
        # samples_per_sec
        "tiers": st.get("tiers"),
        "migrations": st.get("migrations", 0),
        "cache_hit_rate": _cache_hit_rate(),
        # dense-plane sync accounting (grad_sync mode vocabulary): which
        # collective the dense half rode and its modeled bytes/step — the
        # baseline the block-int8-ring WIRE_BENCH rows are priced against
        "sync_mode": st.get("sync_mode", ctx.sync_mode),
        "dense_wire_bytes_per_step": st.get(
            "dense_wire_bytes_per_step", ctx.dense_wire_bytes_per_step()
        ),
    }
    if depth > 1:
        # stage-pipeline accounting: per-stage wall + the overlap fraction
        # are the proof the hoisted feeds actually rode under dense
        # compute (stage_overlap_frac == 0 on a pipe-* record is a finding)
        rec.update(
            pipeline_depth=depth,
            stage_overlap_frac=st.get("stage_overlap_frac", 0.0),
            stage_wall_s=st.get("stage_wall_s"),
            pipeline_stalls=st.get("pipeline_stalls", 0),
            pipeline_drains=st.get("pipeline_drains", 0),
            pipelined_feeds=st.get("pipelined_feeds", 0),
        )
    return rec


def _cache_hit_rate():
    """Process-cumulative HBM hit rate from the tier's metrics (each bench
    mode runs subprocess-isolated, so cumulative == this run)."""
    from persia_tpu.metrics import get_metrics

    snap = get_metrics().snapshot(prefix="persia_tpu_cache_")
    hit = sum((snap.get("persia_tpu_cache_hit_count") or {}).values())
    miss = sum((snap.get("persia_tpu_cache_miss_count") or {}).values())
    return round(hit / (hit + miss), 4) if hit + miss else None


def _zipf_batch_maker(seed: int = 0):
    """Batch factory shared by the cached/hybrid/ps-stream modes (and the
    stage profiler): single-id zipf streams with a stable per-slot hot set,
    plus dense features and labels at the bench shape."""
    from persia_tpu.data import (
        IDTypeFeatureWithSingleID,
        Label,
        NonIDTypeFeature,
        PersiaBatch,
    )

    rng = np.random.default_rng(seed)
    slot_offsets = rng.integers(0, VOCAB, N_SLOTS, dtype=np.uint64)

    def make_batch():
        ids = [
            IDTypeFeatureWithSingleID(
                f"cat_{i}", _zipf_ids(rng, BATCH_SIZE, VOCAB, slot_offsets[i])
            )
            for i in range(N_SLOTS)
        ]
        return PersiaBatch(
            ids,
            non_id_type_features=[
                NonIDTypeFeature(rng.normal(size=(BATCH_SIZE, N_DENSE)).astype(np.float32))
            ],
            labels=[Label(rng.integers(0, 2, (BATCH_SIZE, 1)).astype(np.float32))],
            requires_grad=True,
        )

    return make_batch


def bench_cached():
    """The capacity tier with the HBM write-back cache: vocabulary lives on
    the host C++ PS (beyond-HBM regime, reference README.md:29), the working
    set lives in HBM, the sparse optimizer runs on device, and the previous
    step's eviction write-back overlaps the current step
    (persia_tpu/embedding/hbm_cache.py)."""
    steps = int(os.environ.get("BENCH_CACHED_STEPS", "100"))
    ctx = _cached_tier_ctx()

    make_batch = _zipf_batch_maker()

    # distinct batches (not a short cycle): hit rate comes from the zipf
    # skew + warm cache, not from replaying identical batches
    warmup = max(WARMUP_STEPS, 8)
    batches = [make_batch() for _ in range(warmup + steps)]

    # the whole run stays free of device→host fetches (fetch_final=False):
    # on a remote-attached chip ONE d2h permanently degrades dispatch
    # latency ~200x, so the loss header is synced without a transfer and
    # materialized only after the timed window
    ctx.train_stream(batches[:warmup], fetch_final=False,
                     dispatch_k=_dispatch_k(), pipeline_depth=_pipeline_depth())

    prog = _Progress()
    prog.start()
    t0 = time.perf_counter()
    ctx.train_stream(prog.wrap(batches[warmup:]), fetch_final=False,
                     dispatch_k=_dispatch_k(), pipeline_depth=_pipeline_depth())
    elapsed = time.perf_counter() - t0
    m = ctx.last_metrics()  # d2h outside the timed window
    assert m is not None and np.isfinite(m["loss"])
    return _stream_record(ctx, steps * BATCH_SIZE / elapsed)


def bench_cached_saturated():
    """Steady-state eviction regime on the record: a deliberately small
    cache (default 2^18 rows vs the 26M-sign stream) trained long enough
    (>=600 steps) that fills finish and every step carries real eviction
    write-back traffic — the number the README previously only simulated.
    Same builder/env knobs as the headline cached mode."""
    steps = int(os.environ.get("BENCH_CACHED_SAT_STEPS", "600"))
    os.environ.setdefault("BENCH_CACHE_ROWS", str(1 << 18))
    ctx = _cached_tier_ctx()
    make_batch = _zipf_batch_maker()
    warmup = 8
    batches = [make_batch() for _ in range(warmup + steps)]
    ctx.train_stream(batches[:warmup], fetch_final=False,
                     dispatch_k=_dispatch_k(), pipeline_depth=_pipeline_depth())
    prog = _Progress()
    prog.start()
    t0 = time.perf_counter()
    ctx.train_stream(prog.wrap(batches[warmup:]), fetch_final=False,
                     dispatch_k=_dispatch_k(), pipeline_depth=_pipeline_depth())
    elapsed = time.perf_counter() - t0
    m = ctx.last_metrics()
    assert m is not None and np.isfinite(m["loss"])
    return _stream_record(ctx, steps * BATCH_SIZE / elapsed)


def bench_cached_pipelined():
    """In-order vs stage-pipelined dispatch, A/B'd on ONE record: the same
    cached-tier builder and the same zipf stream driven first with
    pipeline_depth=1 (the historical in-order cadence) and then with the
    MPMD stage pipeline (feeds hoist up to depth-1 steps above the dense
    stage under the hazard ledger, parallel/stage_graph.py). Identical
    dispatch_k on both legs so the only variable is the pipeline; each leg
    gets a fresh ctx and its own warmup so neither inherits the other's
    jit cache or cache fill.

    The record's headline samples_per_sec is the PIPELINED leg (it is the
    mode this bench exists to price); ``baseline_inorder`` carries the
    depth-1 leg's full stream record and ``speedup_vs_inorder`` the ratio,
    so the overlap claim is falsifiable from the committed JSON alone —
    together with the pipelined leg's own stage_overlap_frac and
    feeder_util (a speedup without overlap, or overlap without speedup,
    is a finding)."""
    steps = int(os.environ.get("BENCH_CACHED_PIPE_STEPS", "150"))
    depth = _pipeline_depth()
    if depth <= 1:
        depth = int(os.environ.get("BENCH_PIPE_AB_DEPTH", "4"))
    k = _dispatch_k()
    make_batch = _zipf_batch_maker()
    warmup = 8
    batches = [make_batch() for _ in range(warmup + steps)]

    def leg(d):
        ctx = _cached_tier_ctx()
        ctx.train_stream(batches[:warmup], fetch_final=False,
                         dispatch_k=k, pipeline_depth=d)
        prog = _Progress()
        prog.start()
        t0 = time.perf_counter()
        ctx.train_stream(prog.wrap(batches[warmup:]), fetch_final=False,
                         dispatch_k=k, pipeline_depth=d)
        elapsed = time.perf_counter() - t0
        m = ctx.last_metrics()  # d2h outside the timed window
        assert m is not None and np.isfinite(m["loss"])
        return _stream_record(ctx, steps * BATCH_SIZE / elapsed)

    base = leg(1)
    pipe = leg(depth)
    rec = dict(pipe)
    rec["baseline_inorder"] = base
    if base["samples_per_sec"]:
        rec["speedup_vs_inorder"] = round(
            pipe["samples_per_sec"] / base["samples_per_sec"], 3
        )
    return rec


def bench_ps_stream():
    """The PERSIA-parity fully-async regime: ALL slots PS-resident (no HBM
    cache rows at all), driven through ``CachedTrainCtx.train_stream`` —
    forwards run in the stream's feeder thread, gradients return as bf16
    through the write-back thread's batched CONCURRENT d2h fetches, so the
    pipeline trains under bounded staleness ≤ prefetch + psgrad_batch (the
    reference's lookup-worker regime, forward.rs:640-779).

    Ceiling note: this regime's throughput is bound by the device→host
    gradient wire — samples/sec ≤ d2h_bandwidth / grad_bytes_per_sample.
    On the remote-attached bench chip d2h measures ~5 MB/s (h2d ~1.4 GB/s),
    so with bf16 sample-level grads (26·16·2 B/sample) the link caps the
    mode at ~6k samples/sec REGARDLESS of host/device speed — which is the
    architectural argument for the cached tier (gradients never leave the
    chip). On PCIe-attached hardware (the reference's assumption, ~10 GB/s)
    the same pipeline computes out to ~10M samples/sec of wire headroom.
    """
    steps = int(os.environ.get("BENCH_PS_STREAM_STEPS", "30"))
    ctx = _cached_tier_ctx(ps_all=True)

    make_batch = _zipf_batch_maker()

    warmup = 4
    batches = [make_batch() for _ in range(warmup + steps)]
    ctx.train_stream(batches[:warmup], prefetch=4, psgrad_batch=16,
                     fetch_final=False)
    prog = _Progress(every=5)
    prog.start()
    t0 = time.perf_counter()
    ctx.train_stream(prog.wrap(batches[warmup:]), prefetch=4, psgrad_batch=16,
                     fetch_final=False)
    elapsed = time.perf_counter() - t0
    m = ctx.last_metrics()
    assert m is not None and np.isfinite(m["loss"])
    return steps * BATCH_SIZE / elapsed


def bench_hybrid():
    """The host C++ PS tier driven by the legacy per-step sync path with
    the DataLoader's pipelined lookups (bounded staleness = loader
    staleness); the fully-streamed async number is BENCH_MODE=ps-stream."""
    import optax

    from persia_tpu.config import EmbeddingConfig, SlotConfig
    from persia_tpu.ctx import TrainCtx
    from persia_tpu.data_loader import DataLoader
    from persia_tpu.embedding.native_store import create_store
    from persia_tpu.embedding.optim import Adagrad
    from persia_tpu.embedding.worker import EmbeddingWorker
    from persia_tpu.models import DLRM

    steps = int(os.environ.get("BENCH_HYBRID_STEPS", "100"))
    cfg = EmbeddingConfig(
        slots_config={f"cat_{i}": SlotConfig(dim=EMB_DIM) for i in range(N_SLOTS)},
        feature_index_prefix_bit=8,
    )
    store = create_store(
        "auto", capacity=1 << 25, num_internal_shards=64,
        optimizer=Adagrad(lr=0.05).config, seed=1,
    )
    # device_pooling: only per-DISTINCT rows cross the host↔device link in
    # either direction (~3x fewer wire bytes at this zipf skew than (B,dim)
    # pooled tensors) — the link is this mode's physical ceiling
    worker = EmbeddingWorker(cfg, [store], num_threads=16, device_pooling=True)
    model = DLRM(embedding_dim=EMB_DIM, bottom_mlp=(256, 64, EMB_DIM), top_mlp=(512, 256))
    ctx = TrainCtx(
        model=model, dense_optimizer=optax.adam(1e-3),
        embedding_optimizer=Adagrad(lr=0.05), worker=worker,
        embedding_config=cfg, wire_dtype="bfloat16",
    ).__enter__()

    # single-id contiguous wire (the production shape; also what cached and
    # ps-stream use): distinct batches at 100+ steps would not fit in host
    # RAM as per-sample array lists
    make_batch = _zipf_batch_maker()

    # distinct batches end to end (no short replay cycle: the PS LRU must
    # see the real zipf stream, not a warmed 8-batch loop)
    batches = [make_batch() for _ in range(WARMUP_STEPS + steps)]

    for i in range(WARMUP_STEPS):
        ctx.train_step(batches[i])

    loader = DataLoader(
        iter(batches[WARMUP_STEPS:]), ctx, num_workers=4, staleness=4
    )
    prog = _Progress()
    prog.start()
    t0 = time.perf_counter()
    for tb in loader:
        # defer the header fetch out of the loop (the gradient d2h is
        # inherent to the PS path; the metric d2h is not)
        ctx.train_step_prepared(tb, loader, fetch_metrics=False)
        prog.tick()
    loader.flush()
    elapsed = time.perf_counter() - t0
    m = ctx.last_prepared_metrics()
    assert m is not None and np.isfinite(m["loss"])
    return steps * BATCH_SIZE / elapsed


# -------------------------------------------------- quality-at-throughput


def _quality_data(steps: int):
    """Shared learnable stream (CriteoSynthetic: hidden ground-truth model,
    deterministic per batch_id) split into one training epoch + a held-out
    eval tail. Identical for every tier — same seed, same step budget."""
    from persia_tpu.testing.datasets import CriteoSynthetic

    eval_batches = 4
    ds = CriteoSynthetic(
        num_samples=(steps + eval_batches) * BATCH_SIZE,
        vocab_sizes=[VOCAB] * N_SLOTS,
        seed=5, task_seed=7,
    )
    all_b = list(ds.batches(BATCH_SIZE))
    return all_b[:steps], all_b[steps:]


def _auc_of(preds, labels) -> float:
    from persia_tpu.testing.synthetic import roc_auc

    return float(roc_auc(np.concatenate(labels), np.concatenate(preds)))


def _quality_cached(steps, ps_all=False):
    train_b, eval_b = _quality_data(steps)
    # the SAME builder the throughput benches use (env knobs included):
    # the quality number prices exactly the configuration of the headline
    ctx = _cached_tier_ctx(ps_all=ps_all)
    stream_kw = dict(fetch_final=False)
    if ps_all:
        stream_kw.update(prefetch=4, psgrad_batch=16)
    # first two batches train UNTIMED (jit compilation happens there); the
    # quality epoch still covers every batch exactly once
    ctx.train_stream(train_b[:2], **stream_kw)
    t0 = time.perf_counter()
    ctx.train_stream(train_b[2:], **stream_kw)
    elapsed = time.perf_counter() - t0
    preds, labels = [], []
    for b in eval_b:
        preds.append(ctx.eval_batch(b).reshape(-1))
        labels.append(np.asarray(b.labels[0].data).reshape(-1))
    return {
        "samples_per_sec": round((steps - 2) * BATCH_SIZE / elapsed, 1),
        "auc": round(_auc_of(preds, labels), 10),
    }


def _quality_fused(steps):
    import jax
    import jax.numpy as jnp
    import optax

    from persia_tpu.embedding.optim import Adagrad
    from persia_tpu.models import DLRM
    from persia_tpu.parallel.fused_step import (
        FusedSlotSpec,
        build_fused_eval_step,
        build_fused_train_step,
        init_fused_state,
    )

    train_b, eval_b = _quality_data(steps)
    specs = {f"cat_{i}": FusedSlotSpec(vocab=VOCAB, dim=EMB_DIM) for i in range(N_SLOTS)}
    slot_order = sorted(specs)
    model = DLRM(embedding_dim=EMB_DIM, bottom_mlp=(256, 64, EMB_DIM), top_mlp=(512, 256))
    dense_opt = optax.adam(1e-3)
    sparse_cfg = Adagrad(lr=0.05).config
    step = build_fused_train_step(
        model, dense_opt, sparse_cfg, specs, slot_order, stack=True
    )
    eval_step = build_fused_eval_step(model, specs, slot_order, stack=True)

    def to_fused(b):
        ids = {}
        for f in b.id_type_features:
            flat, counts = f.flat_counts()
            assert len(flat) == len(counts), "quality stream is single-id"
            ids[f.name] = flat.astype(np.int32)
        return {
            "dense": [np.asarray(b.non_id_type_features[0].data, np.float32)],
            "labels": [np.asarray(b.labels[0].data, np.float32)],
            "ids": ids,
        }

    fb = [to_fused(b) for b in train_b]
    state = init_fused_state(
        model, jax.random.PRNGKey(0), specs, fb[0], dense_opt, sparse_cfg,
        stack=True,
    )
    state, (loss, _) = step(state, fb[0])  # compile outside the window
    state = init_fused_state(
        model, jax.random.PRNGKey(0), specs, fb[0], dense_opt, sparse_cfg,
        stack=True,
    )
    t0 = time.perf_counter()
    for b in fb:
        state, (loss, _) = step(state, b)
    jax.block_until_ready(loss)
    elapsed = time.perf_counter() - t0
    preds, labels = [], []
    for b in eval_b:
        f = to_fused(b)
        preds.append(np.asarray(eval_step(state, f)).reshape(-1))
        labels.append(f["labels"][0].reshape(-1))
    return {
        "samples_per_sec": round(steps * BATCH_SIZE / elapsed, 1),
        "auc": round(_auc_of(preds, labels), 10),
    }


# Exact-AUC oracle (the reference CI pins 16-digit AUCs per backend,
# examples/src/adult-income/train.py:146-150): expected held-out AUC per
# tier at the DEFAULT 200-step budget on the given jax platform, fixed
# seeds. Each tier is internally deterministic (the e2e suite asserts
# bit-identical AUC for the hybrid path; the cached stream orders its
# write-backs, and K-step packing is bit-transparent — pinned by
# test_stream_kstep_packing_bitwise_parity); a drift here means a
# semantic change to that tier's math, not noise. Applies only at
# steps=200 on a known platform; set BENCH_QUALITY_STRICT=0 to record
# instead of assert (when changing the math intentionally, rerun and
# update these). Round 6 made int8+error-feedback the ps-stream default
# wire (BENCH_PS_WIRE): that tier's measured-drift tolerance already
# absorbs async-timing variance and the EF wire's small perturbation
# (int8-vs-f32 entry drift measured ~1.7% rel-l2 on the parity test);
# if a chip run lands outside it, re-pin with BENCH_PS_WIRE=bfloat16
# first to separate wire drift from timing drift.
EXPECTED_AUC = {
    # platform -> tier -> (expected AUC, tolerance), recorded on TPU v5e at
    # BENCH_QUALITY_STEPS=200. cached and fused are EXACT (1e-6): the
    # stream's bit-determinism fix makes the cached tier's value stable
    # run-to-run (test_stream_deterministic_under_flush_timing) and the
    # fused tier is one deterministic XLA program. ps-stream trains its
    # slots under bounded staleness with ASYNC gradient returns — the
    # reference's async mode — so its value is timing-dependent BY DESIGN
    # and gets a measured-drift tolerance instead (two strict runs landed
    # 4e-4 apart).
    "tpu": {
        "cached": (0.630926937, 1e-6),
        "ps-stream": (0.6301312949, 5e-3),
        "fused": (0.6302019103, 1e-6),
    },
}


def _check_expected_auc(out: dict, steps: int) -> None:
    import jax

    platform = jax.default_backend()
    strict = os.environ.get("BENCH_QUALITY_STRICT", "1") != "0"
    expected = EXPECTED_AUC.get(platform)
    out["platform"] = platform
    if steps != 200 or expected is None:
        return
    out["expected_auc"] = expected
    if not expected or not strict:
        return
    for tier, (want, tol) in expected.items():
        got = out[tier]["auc"]
        assert abs(got - want) < tol, (
            f"{tier} AUC {got!r} != pinned {want!r} (tol {tol}) on "
            f"{platform} — a semantic change to this tier's math (update "
            f"EXPECTED_AUC only if intentional)"
        )


def bench_quality():
    """The north-star artifact (BASELINE.md): samples/sec AT matched model
    quality. All three tiers train on the IDENTICAL learnable stream
    (CriteoSynthetic, hidden ground truth) for the same step budget and are
    scored by held-out AUC; each runs in its own subprocess (a d2h in one
    tier's eval must not degrade the next tier's dispatch latency). The
    spread assertion makes a throughput 'win' that trades away accuracy
    (e.g. over-aggressive admission gating or wire quantization) fail the
    bench instead of passing silently; the EXPECTED_AUC oracle pins each
    tier's exact value the way the reference CI does. Writes
    BENCH_QUALITY.json."""
    import subprocess
    import sys

    steps = int(os.environ.get("BENCH_QUALITY_STEPS", "200"))
    if steps < 3:
        raise SystemExit(
            "BENCH_QUALITY_STEPS must be >= 3 (the first 2 batches are the "
            "untimed compile warmup)"
        )
    budget_s = float(os.environ.get("BENCH_MODE_BUDGET_S", "1800"))
    out = {}
    for tier in ("cached", "ps-stream", "fused"):
        env = dict(os.environ, BENCH_QUALITY_TIER=tier,
                   BENCH_QUALITY_STEPS=str(steps))
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=budget_s,
            )
        except subprocess.TimeoutExpired:
            raise RuntimeError(
                f"quality tier {tier!r} exceeded its {budget_s:.0f}s budget "
                "(link weather) — rerun with a larger BENCH_MODE_BUDGET_S "
                "or fewer BENCH_QUALITY_STEPS"
            )
        lines = r.stdout.strip().splitlines()
        if r.returncode != 0 or not lines:
            raise RuntimeError(
                f"quality tier {tier!r} failed (rc={r.returncode}):\n"
                + "\n".join(r.stderr.strip().splitlines()[-15:])
            )
        out[tier] = json.loads(lines[-1])
    aucs = [v["auc"] for v in out.values()]
    out["auc_spread"] = round(max(aucs) - min(aucs), 6)
    out["steps"] = steps
    _check_expected_auc(out, steps)
    # the tiers must agree on quality: bf16 wires, touch gating and bounded
    # staleness are allowed to cost at most this much AUC vs the exact
    # all-in-HBM run on the same budget
    assert out["auc_spread"] < 0.02, f"tier AUC spread too wide: {out}"
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_QUALITY.json"), "w") as f:
        json.dump(out, f, indent=1)
    return out


def _quality_tier_main(tier: str, steps: int):
    if tier == "cached":
        res = _quality_cached(steps)
    elif tier == "ps-stream":
        res = _quality_cached(steps, ps_all=True)
    elif tier == "fused":
        res = _quality_fused(steps)
    else:
        raise SystemExit(f"unknown quality tier {tier!r}")
    print(json.dumps(res), flush=True)


def _bench_kill_resume():
    """Trainer kill-resume scenario for the chaos artifact: a journaled
    TrainCtx run is abandoned mid-window (the state a SIGKILLed trainer
    leaves: PS alive, trainer memory gone), then resumed from the newest
    manifest. Records recovery metrics for BOTH resume modes —
    ``rewind`` (PS shards rewound to the fence; the replay re-applies and
    must end bit-identical to an uninterrupted run, asserted here) and
    ``journal`` (PS kept; the replayed window's applies dedupe against
    the apply-journal — journal_hits counts them)."""
    import shutil
    import tempfile

    import optax

    from persia_tpu.config import EmbeddingConfig, SlotConfig
    from persia_tpu.ctx import TrainCtx
    from persia_tpu.embedding.optim import Adagrad
    from persia_tpu.embedding.store import EmbeddingStore
    from persia_tpu.embedding.worker import EmbeddingWorker
    from persia_tpu.jobstate import JobStateManager
    from persia_tpu.models import DNN
    from persia_tpu.testing import SyntheticClickDataset

    STEPS, K, KILL_AT = 12, 4, 9
    cfg = EmbeddingConfig(
        slots_config={"cat_0": SlotConfig(dim=8), "cat_1": SlotConfig(dim=8)},
        feature_index_prefix_bit=8,
    )
    batches = list(
        SyntheticClickDataset(num_samples=STEPS * 64, vocab_sizes=(64, 32), seed=9)
        .batches(64)
    )[:STEPS]

    def make_ctx(stores):
        return TrainCtx(
            model=DNN(dense_mlp_size=8, sparse_mlp_size=16, hidden_sizes=(32,)),
            dense_optimizer=optax.adam(3e-3),
            embedding_optimizer=Adagrad(lr=0.1),
            worker=EmbeddingWorker(cfg, stores), embedding_config=cfg,
        ).__enter__()

    out = {"steps": STEPS, "snapshot_every": K, "killed_at_step": KILL_AT}
    for mode, restore_ps in (("rewind", True), ("journal", False)):
        tmp = tempfile.mkdtemp(prefix=f"bench_resume_{mode}_")
        try:
            stores = [
                EmbeddingStore(capacity=1 << 16, num_internal_shards=4, seed=7)
                for _ in range(2)
            ]
            mgr = JobStateManager(tmp)
            ctx1 = make_ctx(stores)
            ctx1.resume(mgr)
            for i in range(KILL_AT):
                ctx1.train_step(batches[i])
                if (i + 1) % K == 0:
                    ctx1.snapshot_job(mgr)
            del ctx1  # the trainer "dies"; the PS tier survives

            t0 = time.perf_counter()
            ctx2 = make_ctx(stores)
            m = ctx2.resume(mgr, restore_ps=restore_ps)
            resume_s = time.perf_counter() - t0
            for i in range(m.step, STEPS):
                ctx2.train_step(batches[i])
            router = ctx2.worker.lookup_router
            out[mode] = {
                "time_to_resume_s": round(resume_s, 4),
                "steps_replayed": STEPS - m.step,
                "journal_hits": router.journal_skips,
                "resume_info": ctx2.last_resume_info,
            }
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_chaos():
    """Chaos soak: the cached stream against REAL subprocess PS shards
    fronted by fault-injecting proxies (persia_tpu/chaos.py), with a
    scripted mid-run SIGKILL of one shard that a RUNNING self-heal loop
    (``kill_ps_autoheal`` + autopilot Healer promoting a warm standby —
    no scripted restore) must recover from autonomously,
    plus a trainer kill-resume scenario recording recovery metrics
    (time-to-resume, steps replayed, journal hits). The record carries
    the chaos config, the injected-fault counts, breaker trips/states,
    and the degraded-lookup accounting — a soak run is only evidence if
    the artifact shows what was injected and what it cost.

    Spec via ``BENCH_CHAOS`` (see chaos.parse_chaos_spec), e.g.
    ``python bench.py --chaos=reset=0.02,slow=0.01,seed=7``. Data-plane
    content faults (NaN dense features, label flips, sign corruption,
    gradient spikes — persia_tpu/health's detection surface) ride along
    via ``BENCH_CHAOS_DATA`` (chaos.parse_data_chaos_spec) and their
    counts land in the artifact. Runs on the
    CPU-host topology; the number is a liveness/robustness datapoint, not
    a throughput headline."""
    import optax

    from persia_tpu.chaos import (
        ChaosAction, ChaosPlane, DataPlaneChaos, parse_chaos_spec,
        parse_data_chaos_spec,
    )
    from persia_tpu.config import EmbeddingConfig, SlotConfig
    from persia_tpu.data import (
        IDTypeFeatureWithSingleID, Label, NonIDTypeFeature, PersiaBatch,
    )
    from persia_tpu.embedding import hbm_cache as hbm
    from persia_tpu.embedding.optim import Adagrad
    from persia_tpu.embedding.worker import EmbeddingWorker
    from persia_tpu.helper import ServiceCtx
    from persia_tpu.metrics import get_metrics
    from persia_tpu.models import DLRM
    from persia_tpu.service.resilience import ResiliencePolicy, RetryPolicy

    cfg_chaos = parse_chaos_spec(os.environ.get("BENCH_CHAOS", ""))
    # data-plane content faults (BENCH_CHAOS_DATA, chaos.parse_data_chaos_spec
    # format) — poisons the health layer detects, vs. the transport faults
    # above which the crc/breaker layer detects
    data_chaos = DataPlaneChaos(
        parse_data_chaos_spec(os.environ.get("BENCH_CHAOS_DATA", ""))
    )
    data_faults_on = any((
        data_chaos.cfg.nan_prob, data_chaos.cfg.label_flip_prob,
        data_chaos.cfg.sign_corrupt_prob, data_chaos.cfg.spike_prob,
    ))
    steps = int(os.environ.get("BENCH_CHAOS_STEPS", "60"))
    n_slots, batch = 6, 1024
    # corrupt frames must be DETECTED, not silently trained on
    os.environ.setdefault("PERSIA_RPC_CRC", "1")
    emb_cfg = EmbeddingConfig(
        slots_config={
            f"cat_{i}": SlotConfig(dim=EMB_DIM) for i in range(n_slots)
        },
        feature_index_prefix_bit=8,
    )
    policy = ResiliencePolicy(
        retry=RetryPolicy(max_attempts=4, base_s=0.02, max_s=0.5, seed=1),
        breaker_failure_threshold=3, breaker_reset_s=0.5,
        degrade_after_s=10.0, max_degraded_frac=1.0,
    )
    with ServiceCtx(num_parameter_servers=2, num_embedding_workers=0,
                    seed=7) as svc:
        svc.spawn_standby_ps()  # warm standby the healer promotes mid-soak
        plane = ChaosPlane(svc, cfg_chaos, schedule=[
            # fence snapshot + SIGKILL with NO scripted restore: the
            # running Healer (lease+probe detector -> two-phase journal ->
            # promote the warm standby) is the only recovery path — the
            # soak certifies the autonomous loop, not an operator script
            ChaosAction(step=max(steps // 3, 1), op="kill_ps_autoheal",
                        idx=0),
            # arm a seeded kill for the POST-STREAM reshard: the handoff op
            # it lands on comes from the chaos seed (reshard_fault_hook)
            ChaosAction(step=max(2 * steps // 3, 2), op="kill_during_reshard",
                        idx=1, handoff_op="import", op_index=-1),
        ])
        healer = None
        try:
            ps = plane.ps_clients(policy=policy)
            for c in ps:
                c.wait_ready()
            worker = EmbeddingWorker(emb_cfg, ps, policy=policy)
            import tempfile as _tf

            from persia_tpu.autopilot import enable_self_heal
            from persia_tpu.service.failure_detector import DetectorConfig

            # NOTE: the promoted slot is served by a DIRECT StoreClient
            # (the standby's own address) — the dead shard's chaos proxy
            # stays behind, so transport faults stop applying to that slot
            # after the heal; fault_counts() still records what landed
            healer = enable_self_heal(
                svc, _tf.mkdtemp(prefix="bench_selfheal_"),
                router=worker.lookup_router,
                detector_config=DetectorConfig(
                    miss_threshold=3, probe_timeout_s=0.5),
                probe_timeout_s=0.5,
            )
            healer.start(interval_s=0.1)
            ctx = hbm.CachedTrainCtx(
                model=DLRM(embedding_dim=EMB_DIM, bottom_mlp=(64, EMB_DIM),
                           top_mlp=(64,)),
                dense_optimizer=optax.adam(1e-3),
                embedding_optimizer=Adagrad(lr=0.05),
                worker=worker, embedding_config=emb_cfg,
                cache_rows=1 << 14, init_seed=7,
                # content faults poison the model without the on-device
                # finite gate: arm the probe whenever data chaos is on
                health_probe=data_faults_on,
            ).__enter__()
            sentinel = None
            if data_faults_on:
                from persia_tpu.health import SentinelConfig, StreamSentinel

                # count-rungs only (finite skip / clip): the soak measures
                # injected-vs-detected, the rollback ladder is exercised by
                # tests/test_health.py with a jobstate fence to return to
                sentinel = StreamSentinel.from_ctx(
                    ctx, SentinelConfig(z_threshold=1e9, warmup_steps=1 << 30)
                )
            rng = np.random.default_rng(3)
            # BENCH_CHAOS_LOAD (chaos.parse_load_spec) swaps the uniform
            # draw for a seeded load SHAPE — zipf ramp / spike / hot-set
            # rotation — the same schedule autopilot_bench.py soaks under
            load_sched = None
            load_spec = os.environ.get("BENCH_CHAOS_LOAD", "")
            if load_spec:
                from persia_tpu.chaos import LoadSchedule, parse_load_spec

                load_sched = LoadSchedule(parse_load_spec(load_spec))

            def batches():
                for step in range(steps):
                    ids = [
                        IDTypeFeatureWithSingleID(
                            f"cat_{j}",
                            load_sched.signs(step, batch, slot=j)
                            if load_sched is not None
                            else rng.integers(0, 200_000, batch,
                                              dtype=np.uint64),
                        )
                        for j in range(n_slots)
                    ]
                    yield PersiaBatch(
                        ids,
                        non_id_type_features=[NonIDTypeFeature(
                            rng.normal(size=(batch, N_DENSE)).astype(np.float32))],
                        labels=[Label(
                            rng.integers(0, 2, (batch, 1)).astype(np.float32))],
                        requires_grad=True,
                    )

            prog = _Progress(every=10)
            prog.start()
            t0 = time.perf_counter()
            ctx.train_stream(
                prog.wrap(plane.wrap_batches(data_chaos.wrap(batches()))),
                fetch_final=False,
                sentinel=sentinel,
            )
            elapsed = time.perf_counter() - t0
            m = ctx.last_metrics()
            assert m is not None
            # a poisoned final batch legitimately reports a non-finite
            # LOSS (its update was zeroed on device); the health claim is
            # that the non-finite never lands in trained state
            if not data_faults_on:
                assert np.isfinite(m["loss"])
            st = ctx.stream_stats() or {}
            # the healer must not fight the reshard below (2->4->2 swaps
            # every shard's process); stop it once the stream is drained
            healer.stop()
            healer.detector.close()
            heal_rec = {
                "heals": len(healer.mttr_s),
                "mttr_s": [round(x, 4) for x in healer.mttr_s],
                "pending_after": healer.pending() is not None,
                "detector_false_positive_guard":
                    healer.detector.false_positive_guard,
            }
            healer = None
            # elastic reshard under fire: the stream above is drained (the
            # fence), so grow the PS tier 2->4 with the armed seeded kill
            # landing mid-handoff, resume to completion, shrink back. The
            # artifact records the interruption and both runs' op ledgers;
            # reshard_kills rides in faults_injected.
            import tempfile as _tempfile

            js = _tempfile.mkdtemp(prefix="bench_reshard_js_")
            hook = plane.reshard_fault_hook()
            try:
                grow = svc.reshard_ps(4, js, step=steps, fault_hook=hook)
                interrupted = False
            except Exception:  # noqa: BLE001 — the armed kill fired
                interrupted = True
                grow = svc.resume_reshard(js, fault_hook=hook)
            shrink = svc.reshard_ps(2, js, step=steps + 1)
            reshard_rec = {
                "interrupted": interrupted,
                "grow": {k: v for k, v in (grow or {}).items()
                         if k != "skew_splits"},
                "shrink": {k: v for k, v in shrink.items()
                           if k != "skew_splits"},
            }
            return {
                "samples_per_sec": round(steps * batch / elapsed, 1),
                "steps": steps,
                "chaos": cfg_chaos.to_dict(),
                "load": (load_sched.cfg.to_dict()
                         if load_sched is not None else None),
                # trainer kill-resume recovery metrics (jobstate.py):
                # time-to-resume, steps replayed, journal hits per mode
                "kill_resume": _bench_kill_resume(),
                "self_heal": heal_rec,
                "reshard": reshard_rec,
                "faults_injected": plane.fault_counts(),
                "data_chaos": data_chaos.cfg.to_dict(),
                "data_faults_injected": dict(data_chaos.counts),
                "data_faults_detected": (
                    dict(sentinel.stats) if sentinel is not None else {}
                ),
                "degraded_steps": st.get("degraded_steps", 0),
                "degraded_lookup_frac_max": st.get(
                    "degraded_lookup_frac_max", 0.0
                ),
                "breaker_trips": policy.breaker_trips(),
                "breaker_states": policy.breaker_states(),
                "resilience_metrics": get_metrics().snapshot(
                    "persia_tpu_degraded"
                ),
            }
        finally:
            if healer is not None:
                healer.stop()
                healer.detector.close()
            plane.stop()


_BENCHES = {
    "fused": bench_fused,
    "hybrid": bench_hybrid,
    "cached": bench_cached,
    "cached-saturated": bench_cached_saturated,
    "cached-pipelined": bench_cached_pipelined,
    "ps-stream": bench_ps_stream,
    "link": bench_link,
    "chaos": bench_chaos,  # opt-in (--chaos / BENCH_MODE=chaos); not in "all"
}


def _run_mode_isolated(mode: str):
    """Run one mode in a fresh subprocess under a wall-clock budget. Modes
    that fetch device results per step (hybrid) permanently degrade the
    runtime's dispatch latency on a remote-attached chip (~200x, see
    bench_cached docstring) — a shared process would poison every mode
    measured after them. The XLA compile cache keeps the respawn cost to
    process startup.

    A mode that dies or blows its budget (link weather — VERDICT r04 saw
    ps-stream silent for 25 min) degrades to the last ``bench_progress``
    record it printed, labeled ``partial`` — a datapoint, not rc=1."""
    import subprocess
    import sys

    budget_s = float(os.environ.get("BENCH_MODE_BUDGET_S", "1500"))
    env = dict(os.environ, BENCH_MODE=mode)
    timed_out = False
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=budget_s,
        )
        stdout, stderr, rc = out.stdout, out.stderr, out.returncode
    except subprocess.TimeoutExpired as e:
        def _txt(x):
            return x.decode(errors="replace") if isinstance(x, bytes) else (x or "")
        stdout, stderr, rc = _txt(e.stdout), _txt(e.stderr), -1
        timed_out = True
    lines = [l for l in (stdout or "").strip().splitlines() if l.strip()]
    if rc == 0 and lines:
        return json.loads(lines[-1])["modes"][mode]
    for line in reversed(lines):  # salvage the last progress record
        try:
            d = json.loads(line)
        except ValueError:
            continue
        p = d.get("bench_progress") if isinstance(d, dict) else None
        if p:
            return {"partial": True, "timed_out": timed_out, **p}
    return {
        "error": f"rc={rc}" + (" (budget exceeded)" if timed_out else ""),
        "stderr_tail": "\n".join((stderr or "").strip().splitlines()[-6:]),
    }


def _link_class(link: dict) -> str:
    """good/degraded from the measured wires: the wire-bound modes are
    physically capped by d2h bandwidth and dispatch RTT, so a bad tunnel
    must be visible in the artifact, not explained away in prose."""
    if link.get("d2h_MBps", 0.0) < 50.0 or link.get("small_d2h_roundtrip_ms", 1e9) > 20.0:
        return "degraded"
    return "good"


def _mode_value(v):
    """Samples/sec of a completed mode record: a bare number or a dict
    record carrying ``samples_per_sec`` (the stream modes, which also
    report dispatch_mode/feeder_util). Partial/errored records yield
    None — they stay in "modes" but cannot be the headline."""
    if isinstance(v, (int, float)):
        return float(v)
    if (
        isinstance(v, dict) and not v.get("partial")
        and "samples_per_sec" in v
    ):
        return float(v["samples_per_sec"])
    return None


def _result_line(results: dict) -> str:
    # headline = the capacity tier's SATURATED steady-state (eviction
    # write-back on every step), not the flattering fill phase — a reader
    # of the one-line JSON gets the number the 100T regime actually runs
    # at (VERDICT r05 weak #1); the fill figure stays in cached_regimes.
    # "fused" (all-in-HBM) rides along as the in-memory ceiling. Partial /
    # errored modes stay in "modes" but cannot be the headline.
    throughput = {
        k: _mode_value(v) for k, v in results.items()
        if k != "link" and _mode_value(v) is not None
    }
    if "cached-saturated" in throughput:
        headline_mode = "cached-saturated"
    elif "cached" in throughput:
        headline_mode = "cached"
    else:
        headline_mode = next(iter(throughput), "none")
    headline = throughput.get(headline_mode, 0.0)
    flops = _model_train_flops_per_sample()
    out = {
        "metric": "dlrm_criteo_shape_samples_per_sec_per_chip",
        "value": headline,
        # which mode the headline number actually came from: a run where
        # the cached modes degraded to partial (or only a chaos soak ran)
        # must not be readable as a cached-tier measurement
        "headline_mode": headline_mode,
        "value_regime": (
            "saturated" if "cached-saturated" in throughput
            else ("fill" if "cached" in throughput else "first-measured")
        ),
        "unit": "samples/sec",
        "vs_baseline": round(headline / REF_SAMPLES_PER_SEC, 4),
        "model_flops_per_sample": round(flops),
        "mfu": round(headline * flops / V5E_PEAK_FLOPS, 5),
        "modes": results,
    }
    chaos_rec = results.get("chaos")
    if isinstance(chaos_rec, dict) and "chaos" in chaos_rec:
        # chaos soak active: the injected-fault config is part of the
        # record's identity — a reader must never mistake a chaos run's
        # numbers for clean-run numbers
        out["chaos"] = chaos_rec["chaos"]
    if "link" in results and isinstance(results["link"], dict):
        # link health is FIRST-CLASS: a degraded tunnel caps the wire-bound
        # modes and must be legible from the artifact's top level
        link = results["link"]
        out["h2d_MBps"] = link.get("h2d_MBps")
        out["d2h_MBps"] = link.get("d2h_MBps")
        out["small_d2h_roundtrip_ms"] = link.get("small_d2h_roundtrip_ms")
        out["link_class"] = _link_class(link)
        if "platform" in link:
            out["platform"] = link["platform"]
        out["link"] = link
    # the cached tier is honest only as a pair: the 100-step fill-phase
    # number AND the steady-state eviction regime (VERDICT r04 weak #2);
    # the stream records also carry dispatch_mode + feeder_util so a
    # hot-loop regression is visible from this JSON alone
    if "cached" in results and "cached-saturated" in results:
        out["cached_regimes"] = {
            "fill": _mode_value(results["cached"]),
            "saturated": _mode_value(results["cached-saturated"]),
        }
    return json.dumps(out)


def main():
    tier = os.environ.get("BENCH_QUALITY_TIER")
    if tier:  # quality-tier subprocess
        _quality_tier_main(tier, int(os.environ.get("BENCH_QUALITY_STEPS", "200")))
        return
    mode = os.environ.get("BENCH_MODE", "all")
    if mode == "quality":
        out = bench_quality()
        print(json.dumps({"metric": "quality_auc_at_throughput", **out}), flush=True)
        return
    if mode not in ("all", *_BENCHES):
        raise SystemExit(
            f"BENCH_MODE must be one of all/quality/{'/'.join(_BENCHES)}, got {mode!r}"
        )
    results = {}
    if mode == "all":
        # headline mode FIRST, and a cumulative result line after EVERY
        # mode: a harness that parses the last stdout line still gets a
        # complete record if the run is cut off mid-suite
        # headline (cached) first, then everything else in _BENCHES; the
        # link measurement LAST (same chip session, closest conditions to
        # the wire-bound modes it contextualizes)
        order = sorted(
            (n for n in _BENCHES if n != "chaos"),  # chaos is opt-in only
            key=lambda n: (n == "link", n != "cached"),
        )
        for m in order:
            r = _run_mode_isolated(m)
            results[m] = round(r, 1) if isinstance(r, float) else r
            print(_result_line(results), flush=True)
        return
    r = _BENCHES[mode]()
    results[mode] = round(r, 1) if isinstance(r, float) else r
    print(_result_line(results), flush=True)


if __name__ == "__main__":
    import sys

    # --chaos[=spec] CLI: run the chaos soak mode with the given fault
    # spec (chaos.parse_chaos_spec format); env vars still override
    for _a in sys.argv[1:]:
        if _a == "--chaos":
            os.environ.setdefault("BENCH_CHAOS", "reset=0.02,slow=0.01,seed=7")
            os.environ.setdefault("BENCH_MODE", "chaos")
        elif _a.startswith("--chaos="):
            os.environ["BENCH_CHAOS"] = _a.split("=", 1)[1]
            os.environ.setdefault("BENCH_MODE", "chaos")
        else:
            raise SystemExit(f"unknown argument {_a!r} (supported: --chaos[=spec])")
    main()

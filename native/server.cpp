// persia_tpu native RPC server: the parameter-server data plane in C++.
//
// Capability parity with the reference's compiled service stack — hyper
// HTTP + speedy zero-copy bodies + optional lz4 serving tokio services
// (`/root/reference/rust/others/persia-rpc/src/lib.rs:68-145`,
// `persia-embedding-server/src/bin/persia-embedding-parameter-server.rs`).
// The round-1 Python socketserver stays as the control plane; this server
// owns the listener and handles the HOT methods (ping / lookup_batched /
// update_batched) entirely in C++ threads — frame parse, dispatch, store
// call (via dlopen'd libpersia_ps.so), wire-dtype conversion, optional lz4
// reply compression, writev reply — so per-batch traffic never takes the
// GIL. Unknown methods bounce to a registered Python callback (ctypes
// acquires the GIL for us), which serves checkpoints/config/admin exactly
// as before.
//
// Framing (shared with persia_tpu/service/rpc.py):
//   request:  u32 total | u8 flags | u16 mlen | method | payload
//             flags bits 0-1: codec (0 none, 1 zlib*, 2 lz4); bit 7:
//             client accepts compressed replies   (*zlib → Python fallback)
//   reply:    u32 total | u8 status (low nibble 0 ok/1 err; high: codec) | payload
//
// Batched message bodies (persia_tpu/service/proto.py):
//   lookup_batched:  u8 train | u8 dtype_code | u16 n | u32 dims[n]
//                    | i64 key_ofs[n+1] | u64 signs[...]
//     reply: rows in dtype_code (0 f32, 1 f16, 2 bf16)
//   update_batched:  u8 dtype_code | u16 n | u32 dims[n] | i32 opt_groups[n]
//                    | i64 key_ofs[n+1] | u64 signs | grads in dtype_code

#include <arpa/inet.h>
#include <dlfcn.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {
int64_t lz4_compress_bound(int64_t n);
int64_t lz4_compress(const uint8_t* src, int64_t n, uint8_t* dst, int64_t cap);
int64_t lz4_decompress(const uint8_t* src, int64_t n, uint8_t* dst, int64_t cap);
}

namespace {

// ---------------------------------------------------------- wire dtypes

inline uint16_t f32_to_f16_bits(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  const uint32_t sign = (x >> 16) & 0x8000u;
  uint32_t absx = x & 0x7FFFFFFFu;
  if (absx >= 0x7F800000u) {  // inf/nan
    return (uint16_t)(sign | 0x7C00u | (absx > 0x7F800000u ? 0x200u : 0));
  }
  if (absx >= 0x477FF000u) return (uint16_t)(sign | 0x7C00u);  // overflow → inf
  if (absx < 0x38800000u) {  // subnormal / zero
    if (absx < 0x33000000u) return (uint16_t)sign;
    const int shift = 126 - (int)(absx >> 23);
    uint32_t mant = (absx & 0x7FFFFFu) | 0x800000u;
    const uint32_t rounded = mant >> (shift + 1);
    const uint32_t rem = mant & ((2u << shift) - 1);
    const uint32_t half = 1u << shift;
    uint32_t out = rounded;
    if (rem > half || (rem == half && (rounded & 1))) ++out;
    return (uint16_t)(sign | out);
  }
  // normal: round to nearest even
  uint32_t mant = absx + 0xFFFu + ((absx >> 13) & 1u);
  return (uint16_t)(sign | ((mant - 0x38000000u) >> 13));
}

inline float f16_bits_to_f32(uint16_t h) {
  const uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t mant = h & 0x3FFu;
  uint32_t out;
  if (exp == 0) {
    if (mant == 0) {
      out = sign;
    } else {  // subnormal: normalize
      int e = -1;
      do {
        ++e;
        mant <<= 1;
      } while (!(mant & 0x400u));
      out = sign | ((uint32_t)(113 - e) << 23) | ((mant & 0x3FFu) << 13);
    }
  } else if (exp == 31) {
    out = sign | 0x7F800000u | (mant << 13);
  } else {
    out = sign | ((exp + 112) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &out, 4);
  return f;
}

inline uint16_t f32_to_bf16_bits(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  if ((x & 0x7F800000u) == 0x7F800000u) return (uint16_t)(x >> 16);  // inf/nan
  // round to nearest even
  const uint32_t rounding = 0x7FFFu + ((x >> 16) & 1u);
  return (uint16_t)((x + rounding) >> 16);
}

inline float bf16_bits_to_f32(uint16_t b) {
  const uint32_t out = (uint32_t)b << 16;
  float f;
  std::memcpy(&f, &out, 4);
  return f;
}

void f32_to_wire(const float* src, int64_t n, uint8_t* dst, int code) {
  uint16_t* d = (uint16_t*)dst;
  if (code == 1) {
    for (int64_t i = 0; i < n; ++i) d[i] = f32_to_f16_bits(src[i]);
  } else {
    for (int64_t i = 0; i < n; ++i) d[i] = f32_to_bf16_bits(src[i]);
  }
}

void wire_to_f32(const uint8_t* src, int64_t n, float* dst, int code) {
  const uint16_t* s = (const uint16_t*)src;
  if (code == 1) {
    for (int64_t i = 0; i < n; ++i) dst[i] = f16_bits_to_f32(s[i]);
  } else {
    for (int64_t i = 0; i < n; ++i) dst[i] = bf16_bits_to_f32(s[i]);
  }
}

// Wire-supplied group layout: reject anything that could size buffers or
// offsets negatively (corrupt/hostile frames must error, not scribble).
bool layout_ok(const int64_t* key_ofs, const uint32_t* dims, int ng,
               int64_t* total_out) {
  if (ng < 0 || ng > 0xFFFF) return false;
  if (ng && key_ofs[0] != 0) return false;
  int64_t total = 0;
  for (int g = 0; g < ng; ++g) {
    if (key_ofs[g + 1] < key_ofs[g]) return false;
    if (dims[g] == 0 || dims[g] > (1u << 20)) return false;
    total += (key_ofs[g + 1] - key_ofs[g]) * (int64_t)dims[g];
    if (total > ((int64_t)1 << 33)) return false;  // > 32 GiB of f32: nonsense
  }
  *total_out = total;
  return true;
}

// ------------------------------------------------------------- ps symbols

struct PsFns {
  void (*lookup_batched)(void*, const uint64_t*, const int64_t*, const uint32_t*,
                         const int64_t*, int32_t, int, float*);
  int (*update_batched)(void*, const uint64_t*, const int64_t*, const uint32_t*,
                        const float*, const int64_t*, const int32_t*, int32_t);
};

// ------------------------------------------------------------- the server

constexpr uint8_t FLAG_CODEC_MASK = 0x03;
constexpr uint8_t FLAG_REPLY_OK = 0x80;
constexpr int64_t MAX_FRAME = (int64_t)1 << 31;

struct Server;

// Python fallback: called with (method, payload, len, reply_ctx); Python
// must invoke net_reply(reply_ctx, status, data, len) before returning.
typedef void (*FallbackCb)(const char* method, const uint8_t* payload,
                           int64_t len, void* reply_ctx);

struct ReplyCtx {
  std::vector<uint8_t> data;
  int status = 1;
  bool set = false;
};

struct Server {
  // atomic, and stop() closes it only after every thread that might read it
  // has been joined — the shutdown-RPC path in a connection thread calls
  // ::shutdown on it, and with fd-number reuse a concurrent close could
  // redirect that shutdown to an unrelated descriptor
  std::atomic<int> listen_fd{-1};
  int port = 0;
  void* store = nullptr;
  PsFns ps{};
  FallbackCb fallback = nullptr;
  int64_t compress_threshold = 1 << 20;
  std::atomic<bool> stopping{false};
  std::thread accept_thread;
  std::mutex conn_mu;
  // one slot per accepted connection; `done` flips when its thread is about
  // to exit, so the accept loop can reap zombies (long-lived servers see
  // reconnect churn — unjoined threads would accumulate forever)
  struct ConnSlot {
    std::thread t;
    std::atomic<bool> done{false};
  };
  std::vector<std::unique_ptr<ConnSlot>> conns;
  std::vector<int> live_fds;  // open connection sockets (for stop() wakeup)

  void reap_finished() {
    std::lock_guard<std::mutex> g(conn_mu);
    for (size_t i = 0; i < conns.size();) {
      if (conns[i]->done.load(std::memory_order_acquire)) {
        if (conns[i]->t.joinable()) conns[i]->t.join();
        conns.erase(conns.begin() + i);
      } else {
        ++i;
      }
    }
  }

  void track_fd(int fd, bool add) {
    std::lock_guard<std::mutex> g(conn_mu);
    if (add) {
      live_fds.push_back(fd);
      // a connection accepted concurrently with stop() missed its wakeup
      // sweep — unblock it here so the destructor's join can't hang
      if (stopping.load(std::memory_order_relaxed)) ::shutdown(fd, SHUT_RDWR);
    } else {
      for (auto it = live_fds.begin(); it != live_fds.end(); ++it)
        if (*it == fd) {
          live_fds.erase(it);
          break;
        }
    }
  }

  ~Server() { stop(); }

  // Idempotent, and ALWAYS joins: the shutdown RPC handler sets `stopping`
  // from a connection thread, so stop() must not early-return on the flag
  // — a joinable std::thread destructing is std::terminate.
  void stop() {
    stopping.store(true);
    const int lfd = listen_fd.exchange(-1);
    if (lfd >= 0) ::shutdown(lfd, SHUT_RDWR);
    if (accept_thread.joinable()) accept_thread.join();
    std::vector<std::unique_ptr<ConnSlot>> local;
    {
      std::lock_guard<std::mutex> g(conn_mu);
      local.swap(conns);
      // wake connection threads parked in recv (join would hang otherwise);
      // threads own the close — shutdown only unblocks them
      for (int fd : live_fds) ::shutdown(fd, SHUT_RDWR);
    }
    for (auto& c : local)
      if (c->t.joinable()) c->t.join();
    // all readers of listen_fd are joined; only now is close (and hence
    // kernel fd-number reuse) safe
    if (lfd >= 0) ::close(lfd);
  }
};

bool recv_exact(int fd, uint8_t* buf, int64_t n) {
  while (n > 0) {
    ssize_t r = ::recv(fd, buf, (size_t)n, 0);
    if (r <= 0) return false;
    buf += r;
    n -= r;
  }
  return true;
}

bool send_all(int fd, const struct iovec* iov, int iovcnt) {
  struct iovec local[8];
  for (int i = 0; i < iovcnt; ++i) local[i] = iov[i];
  int idx = 0;
  while (idx < iovcnt) {
    ssize_t w = ::writev(fd, local + idx, iovcnt - idx);
    if (w < 0) return false;
    while (idx < iovcnt && (size_t)w >= local[idx].iov_len) {
      w -= local[idx].iov_len;
      ++idx;
    }
    if (idx < iovcnt && w > 0) {
      local[idx].iov_base = (uint8_t*)local[idx].iov_base + w;
      local[idx].iov_len -= w;
    }
  }
  return true;
}

// reply with optional lz4 compression (u32 orig | blocks body, codec id 2)
bool send_reply(int fd, uint8_t status, const uint8_t* body, int64_t blen,
                bool client_ok, int64_t threshold) {
  std::vector<uint8_t> comp;
  if (status == 0 && client_ok && blen >= threshold) {
    comp.resize(4 + (size_t)lz4_compress_bound(blen));
    int64_t n = lz4_compress(body, blen, comp.data() + 4, (int64_t)comp.size() - 4);
    if (n > 0 && n + 4 < blen) {
      uint32_t orig = (uint32_t)blen;
      std::memcpy(comp.data(), &orig, 4);
      body = comp.data();
      blen = n + 4;
      status |= 2u << 4;
    }
  }
  uint32_t total = (uint32_t)(blen + 1);
  uint8_t head[5];
  std::memcpy(head, &total, 4);
  head[4] = status;
  struct iovec iov[2] = {{head, 5}, {(void*)body, (size_t)blen}};
  return send_all(fd, iov, blen ? 2 : 1);
}

bool handle_lookup_batched(Server* s, int fd, const uint8_t* p, int64_t n,
                           bool client_ok) {
  if (n < 4) return false;
  const uint8_t train = p[0];
  const uint8_t code = p[1];
  uint16_t ng;
  std::memcpy(&ng, p + 2, 2);
  int64_t off = 4;
  if (off + 4 * (int64_t)ng + 8 * ((int64_t)ng + 1) > n) return false;
  // wire fields are byte-packed: copy to aligned scratch before typed use
  thread_local std::vector<uint32_t> dims_v;
  dims_v.resize(ng);
  std::memcpy(dims_v.data(), p + off, 4 * (size_t)ng);
  const uint32_t* dims = dims_v.data();
  off += 4 * ng;
  thread_local std::vector<int64_t> key_ofs;
  key_ofs.resize(ng + 1);
  std::memcpy(key_ofs.data(), p + off, 8 * ((size_t)ng + 1));
  off += 8 * ((int64_t)ng + 1);
  const int64_t n_signs = ng ? key_ofs[ng] : 0;
  // divide form: 8 * n_signs would wrap for hostile key_ofs[ng] >= 2^60,
  // passing the check and then killing the process in resize()
  if (n_signs < 0 || n_signs > (n - off) / 8) return false;
  thread_local std::vector<uint64_t> signs;
  signs.resize((size_t)n_signs);
  std::memcpy(signs.data(), p + off, 8 * (size_t)n_signs);

  int64_t total = 0;
  if (!layout_ok(key_ofs.data(), dims, ng, &total)) return false;
  thread_local std::vector<int64_t> out_ofs;
  out_ofs.resize(ng);
  int64_t acc = 0;
  for (int g = 0; g < ng; ++g) {
    out_ofs[g] = acc;
    acc += (key_ofs[g + 1] - key_ofs[g]) * (int64_t)dims[g];
  }
  thread_local std::vector<float> rows;
  rows.resize((size_t)total);
  s->ps.lookup_batched(s->store, signs.data(), key_ofs.data(), dims,
                       out_ofs.data(), ng, train, rows.data());
  if (code == 0) {
    return send_reply(fd, 0, (const uint8_t*)rows.data(), total * 4, client_ok,
                      s->compress_threshold);
  }
  thread_local std::vector<uint8_t> wire;
  wire.resize((size_t)total * 2);
  f32_to_wire(rows.data(), total, wire.data(), code);
  return send_reply(fd, 0, wire.data(), total * 2, client_ok,
                    s->compress_threshold);
}

bool handle_update_batched(Server* s, int fd, const uint8_t* p, int64_t n,
                           bool client_ok) {
  if (n < 3) return false;
  const uint8_t code = p[0];
  uint16_t ng;
  std::memcpy(&ng, p + 1, 2);
  int64_t off = 3;
  if (off + 8 * (int64_t)ng + 8 * ((int64_t)ng + 1) > n) return false;
  thread_local std::vector<uint32_t> dims_v;
  dims_v.resize(ng);
  std::memcpy(dims_v.data(), p + off, 4 * (size_t)ng);
  const uint32_t* dims = dims_v.data();
  off += 4 * ng;
  thread_local std::vector<int32_t> ogs;
  ogs.resize(ng);
  std::memcpy(ogs.data(), p + off, 4 * (size_t)ng);
  off += 4 * ng;
  thread_local std::vector<int64_t> key_ofs;
  key_ofs.resize(ng + 1);
  std::memcpy(key_ofs.data(), p + off, 8 * ((size_t)ng + 1));
  off += 8 * ((int64_t)ng + 1);
  const int64_t n_signs = ng ? key_ofs[ng] : 0;
  if (n_signs < 0 || n_signs > (n - off) / 8) return false;
  thread_local std::vector<uint64_t> signs;
  signs.resize((size_t)n_signs);
  std::memcpy(signs.data(), p + off, 8 * (size_t)n_signs);
  off += 8 * n_signs;

  int64_t total = 0;
  if (!layout_ok(key_ofs.data(), dims, ng, &total)) return false;
  thread_local std::vector<int64_t> grad_ofs;
  grad_ofs.resize(ng);
  int64_t acc = 0;
  for (int g = 0; g < ng; ++g) {
    grad_ofs[g] = acc;
    acc += (key_ofs[g + 1] - key_ofs[g]) * (int64_t)dims[g];
  }
  const int64_t want = total * (code ? 2 : 4);
  if (off + want > n) return false;
  const float* grads;
  thread_local std::vector<float> gbuf;
  if (code == 0) {
    gbuf.resize((size_t)total);
    std::memcpy(gbuf.data(), p + off, (size_t)total * 4);  // align
    grads = gbuf.data();
  } else {
    gbuf.resize((size_t)total);
    wire_to_f32(p + off, total, gbuf.data(), code);
    grads = gbuf.data();
  }
  int rc = s->ps.update_batched(s->store, signs.data(), key_ofs.data(), dims,
                                grads, grad_ofs.data(), ogs.data(), ng);
  if (rc != 0) {
    static const char kErr[] = "remote error: no optimizer registered";
    return send_reply(fd, 1, (const uint8_t*)kErr, sizeof(kErr) - 1, false, 0);
  }
  return send_reply(fd, 0, (const uint8_t*)"ok", 2, false, 0);
}

void serve_conn_inner(Server* s, int fd);

void serve_conn(Server* s, int fd, Server::ConnSlot* slot) {
  // close ownership lives HERE (after untrack): closing inside the inner
  // loop would let the kernel reuse the fd number while stop() still holds
  // it in live_fds and shutdown()s an unrelated connection
  s->track_fd(fd, true);
  serve_conn_inner(s, fd);
  s->track_fd(fd, false);
  ::close(fd);
  slot->done.store(true, std::memory_order_release);
}

void serve_conn_inner(Server* s, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::vector<uint8_t> frame;
  std::vector<uint8_t> raw;
  while (!s->stopping.load(std::memory_order_relaxed)) {
    uint8_t head[4];
    if (!recv_exact(fd, head, 4)) break;
    uint32_t total;
    std::memcpy(&total, head, 4);
    if ((int64_t)total > MAX_FRAME || total < 3) break;
    frame.resize(total);
    if (!recv_exact(fd, frame.data(), total)) break;
    const uint8_t flags = frame[0];
    uint16_t mlen;
    std::memcpy(&mlen, frame.data() + 1, 2);
    if ((int64_t)3 + mlen > (int64_t)total) break;
    char method[64];
    const size_t mcopy = mlen < sizeof(method) - 1 ? mlen : sizeof(method) - 1;
    std::memcpy(method, frame.data() + 3, mcopy);
    method[mcopy] = 0;
    const uint8_t* payload = frame.data() + 3 + mlen;
    int64_t plen = (int64_t)total - 3 - mlen;
    const bool client_ok = (flags & FLAG_REPLY_OK) != 0;
    const uint8_t codec = flags & FLAG_CODEC_MASK;
    if (codec == 2) {  // lz4: u32 orig | blocks
      if (plen < 4) break;
      uint32_t orig;
      std::memcpy(&orig, payload, 4);
      raw.resize(orig);
      if (lz4_decompress(payload + 4, plen - 4, raw.data(), orig) != (int64_t)orig)
        break;
      payload = raw.data();
      plen = orig;
    } else if (codec != 0) {
      // zlib (legacy peers): route through the Python fallback, which
      // decompresses with the portable codec module
      ReplyCtx ctx;
      std::string m = std::string("__zlib__:") + method;
      s->fallback(m.c_str(), payload, plen, &ctx);
      if (!ctx.set ||
          !send_reply(fd, (uint8_t)ctx.status, ctx.data.data(),
                      (int64_t)ctx.data.size(), client_ok && ctx.status == 0,
                      s->compress_threshold))
        break;
      continue;
    }
    bool ok;
    if (std::strcmp(method, "ping") == 0) {
      ok = send_reply(fd, 0, (const uint8_t*)"pong", 4, false, 0);
    } else if (std::strcmp(method, "lookup_batched") == 0) {
      ok = handle_lookup_batched(s, fd, payload, plen, client_ok);
    } else if (std::strcmp(method, "update_batched") == 0) {
      ok = handle_update_batched(s, fd, payload, plen, client_ok);
    } else {
      ReplyCtx ctx;
      s->fallback(method, payload, plen, &ctx);
      ok = ctx.set && send_reply(fd, (uint8_t)ctx.status, ctx.data.data(),
                                 (int64_t)ctx.data.size(),
                                 client_ok && ctx.status == 0,
                                 s->compress_threshold);
      if (ok && std::strcmp(method, "shutdown") == 0) {
        // wake the accept loop; fd close + joins belong to the wrapper and
        // stop(), which the Python side drives
        s->stopping.store(true);
        // shutdown only, never close — stop() owns the close, and defers it
        // past the join of this very thread so the fd number can't be reused
        // under us
        const int lfd = s->listen_fd.load();
        if (lfd >= 0) ::shutdown(lfd, SHUT_RDWR);
        return;
      }
    }
    if (!ok) break;
  }
}

void accept_loop(Server* s) {
  while (!s->stopping.load(std::memory_order_relaxed)) {
    const int lfd = s->listen_fd.load(std::memory_order_relaxed);
    if (lfd < 0) return;
    int fd = ::accept(lfd, nullptr, nullptr);
    if (fd < 0) {
      if (s->stopping.load(std::memory_order_relaxed)) return;
      continue;
    }
    s->reap_finished();
    auto slot = std::make_unique<Server::ConnSlot>();
    Server::ConnSlot* raw = slot.get();
    // start the thread BEFORE publishing the slot: reap/stop must only ever
    // see joinable threads. If stop() swapped `conns` in between, the
    // ~Server second stop() joins this late slot (track_fd wakes its recv).
    raw->t = std::thread([s, fd, raw] { serve_conn(s, fd, raw); });
    std::lock_guard<std::mutex> g(s->conn_mu);
    s->conns.push_back(std::move(slot));
  }
}

}  // namespace

extern "C" {

void net_reply(void* reply_ctx, int status, const uint8_t* data, int64_t len) {
  ReplyCtx* ctx = (ReplyCtx*)reply_ctx;
  ctx->status = status;
  ctx->data.assign(data, data + (len > 0 ? len : 0));
  ctx->set = true;
}

// Start the native server. ps_so_path: path to libpersia_ps.so (dlopened
// for the store entry points). Returns an opaque handle or null.
void* net_server_start(int port, void* store_handle, const char* ps_so_path,
                       FallbackCb fallback, int64_t compress_threshold) {
  void* so = dlopen(ps_so_path, RTLD_NOW | RTLD_GLOBAL);
  if (!so) return nullptr;
  Server* s = new Server();
  s->ps.lookup_batched = (decltype(s->ps.lookup_batched))dlsym(so, "ps_lookup_batched");
  s->ps.update_batched = (decltype(s->ps.update_batched))dlsym(so, "ps_update_batched");
  if (!s->ps.lookup_batched || !s->ps.update_batched) {
    delete s;
    return nullptr;
  }
  s->store = store_handle;
  s->fallback = fallback;
  s->compress_threshold = compress_threshold;

  const int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (::bind(lfd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      ::listen(lfd, 128) != 0) {
    ::close(lfd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(lfd, (sockaddr*)&addr, &alen);
  s->listen_fd.store(lfd);
  s->port = ntohs(addr.sin_port);
  s->accept_thread = std::thread(accept_loop, s);
  return s;
}

int net_server_port(void* h) { return h ? ((Server*)h)->port : -1; }

void net_server_stop(void* h) {
  if (!h) return;
  Server* s = (Server*)h;
  s->stop();
  delete s;
}

}  // extern "C"

// persia_tpu native HBM-cache directory.
//
// Host-side bookkeeping for the write-back HBM embedding cache
// (persia_tpu/embedding/hbm_cache.py): a fixed-capacity LRU map from
// embedding sign -> device cache row. The device holds the actual rows
// ([emb | optimizer state] in HBM); this directory decides, per batch of
// deduplicated signs, which rows hit, which signs miss (and which cache row
// each miss is assigned), and which resident signs get evicted to make room
// (their rows are read back from the device and written to the host PS —
// the write-back).
//
// This plays the role the reference's embedding-worker forward buffers and
// PS LRU jointly play (rust/persia-embedding-server/.../eviction_map.rs
// O(1) LRU over a slab), re-targeted at a device-resident row pool:
// row index == slab slot, intrusive doubly-linked LRU, open-addressing
// hash with backward-shift deletion (same scheme as native/ps.cpp).
//
// C ABI only (ctypes-friendly).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <memory>
#include <mutex>
#include <new>
#include <thread>
#include <vector>

#if defined(__linux__)
#include <pthread.h>
#include <sched.h>
#include <unistd.h>
#endif

namespace {

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// SWAR helpers for the 8-at-a-time tag probe: broadcast one byte across a
// u64 lane group, and mark (with 0x80 in that byte) every zero byte of v.
// The haszero trick only borrows INTO a byte when that byte is zero, so the
// markers are exact for our operands (tags are never 0x01..0x7F: a live tag
// always has its 0x80 occupancy bit set, an empty tag is 0x00).
inline uint64_t swar_bcast8(uint8_t b) {
  return (uint64_t)b * 0x0101010101010101ULL;
}
inline uint64_t swar_zero_bytes(uint64_t v) {
  return (v - 0x0101010101010101ULL) & ~v & 0x8080808080808080ULL;
}

// PERSIA_FEED_PROBE=scalar forces the legacy one-slot-at-a-time probe
// (golden reference); anything else (default) selects the SIMD tag-array
// walk. Read once per process — per-handle overrides ride the
// cache_set_probe_mode exports.
inline int default_probe_mode() {
  static const int mode = [] {
    const char* e = std::getenv("PERSIA_FEED_PROBE");
    return (e != nullptr && std::strcmp(e, "scalar") == 0) ? 0 : 1;
  }();
  return mode;
}

struct Cache {
  int64_t capacity = 0;
  int64_t count = 0;
  // per-row metadata (row index == slab slot); prev/next interleaved in one
  // 16-byte node so an LRU unlink touches one cache line, not two
  std::vector<uint64_t> row_sign;
  struct Link { int64_t prev, next; };
  std::vector<Link> lru;
  int64_t lru_head = -1, lru_tail = -1;
  std::vector<int64_t> free_rows;
  // open addressing sign -> row, sign and row interleaved in one 16-byte
  // bucket so a probe costs ONE cache-line fetch (this directory is
  // memory-latency-bound: the table spans tens of MB at production
  // capacities and every probe is a random access)
  struct Slot { uint64_t sign; int64_t row; };  // row -1 = empty
  std::vector<Slot> table;
  uint64_t mask = 0;
  // SIMD probe layout (round 17): a 1-byte tag per table slot, kept in a
  // separate dense array so one cache-line fetch covers 64 probe positions
  // instead of 4. tag = 0x80 | top-7-bits of splitmix64(sign) (the home
  // slot uses the LOW bits, so tag and placement are independent); 0x00 =
  // empty. The probe loads 8 tags as one u64 and resolves match/empty
  // lanes with SWAR compares; only tag-matching lanes touch the 16-byte
  // payload table. Tags are maintained on EVERY mutation regardless of
  // probe_mode, so the mode can flip at any time and both probes always
  // see a coherent layout. The 8 bytes past the end mirror tags[0..8) so
  // a group load starting near the top wraps without a branch.
  std::vector<uint8_t> tags;
  // 0 = scalar probe (golden reference), 1 = SIMD tag walk + probe-wave
  // passes in the sharded feeder. Same results bit-for-bit by
  // construction: linear probing's result depends only on slot contents,
  // never on how many slots a step inspects at once.
  int probe_mode = default_probe_mode();
  // touch-gated admission (the reference's admit_probability analogue,
  // persia-embedding-config HyperParameters): a sign is only ADMITTED on
  // its admit_touches'th distinct-batch touch; earlier touches map to the
  // pad row (forward contributes zero, gradient dropped — exactly the
  // reference's non-admitted-sign semantics). Counters live in a compact
  // counting-Bloom byte table (hash-indexed, no sign storage): collisions
  // can only admit EARLY, never block admission. Slashes steady-state
  // eviction write-backs under zipf traffic (one-hit wonders never enter).
  int64_t admit_touches = 1;  // 1 = admit on first touch (exact parity)
  std::vector<uint8_t> touch_counts;
  uint64_t touch_mask = 0;

  explicit Cache(int64_t cap) : capacity(cap) {
    row_sign.assign(cap, 0);
    lru.assign(cap, Link{-1, -1});
    free_rows.reserve(cap);
    for (int64_t r = cap - 1; r >= 0; --r) free_rows.push_back(r);
    uint64_t tsize = 16;
    while (tsize < (uint64_t)cap * 2) tsize <<= 1;
    table.assign(tsize, Slot{0, -1});
    tags.assign(tsize + 8, 0);  // +8: wraparound mirror of tags[0..8)
    mask = tsize - 1;
  }

  void ensure_touch_table() {
    if (touch_counts.empty()) {
      uint64_t tsize = 16;
      while (tsize < (uint64_t)capacity * 4) tsize <<= 1;
      touch_counts.assign(tsize, 0);
      touch_mask = tsize - 1;
    }
  }

  inline uint64_t touch_idx(uint64_t sign) const {
    return splitmix64(sign ^ 0x5851F42D4C957F2DULL) & touch_mask;
  }

  // true -> admit now; false -> bypass this batch (counter bumped)
  inline bool touch_admits(uint64_t sign) {
    if (admit_touches <= 1) return true;
    uint8_t& c = touch_counts[touch_idx(sign)];
    if (c + 1 >= admit_touches) { c = 0; return true; }
    ++c;
    return false;
  }

  inline uint64_t home(uint64_t sign) const { return splitmix64(sign) & mask; }

  static inline uint8_t tag_of_hash(uint64_t h) {
    return (uint8_t)(0x80u | (uint32_t)(h >> 57));
  }

  // every tag write goes through here so the wrap mirror stays coherent
  inline void tag_set(uint64_t i, uint8_t v) {
    tags[i] = v;
    if (i < 8) tags[mask + 1 + i] = v;
  }

  int64_t find_pos_scalar(uint64_t sign) const {
    uint64_t i = home(sign);
    while (table[i].row >= 0) {
      if (table[i].sign == sign) return (int64_t)i;
      i = (i + 1) & mask;
    }
    return -1;
  }

  // SIMD tag walk with a precomputed sign hash: scan 8 tags per u64 load,
  // resolve candidate lanes in probe order, stop at the first empty lane.
  // Returns exactly what find_pos_scalar returns: linear probing's answer
  // ("the slot holding `sign` before the first empty slot from home") is a
  // property of the table contents alone, so inspecting 8 slots at a time
  // cannot change it — the lane mask discards candidates past the first
  // empty lane, and a tag hit (7-bit, ~1/128 false-positive rate) is
  // confirmed against the payload sign before it counts.
  int64_t find_pos_simd_h(uint64_t sign, uint64_t h) const {
    // home fast path: at the table's <=50% load factor most chains are one
    // slot long, and the home payload line is already prefetched by the
    // probe-wave stage — answer chain-length-1 probes with the SAME single
    // load the scalar walk pays, without touching the tag array's line
    const uint64_t home_p = h & mask;
    const Slot& s0 = table[home_p];
    if (s0.row < 0) return -1;
    if (s0.sign == sign) return (int64_t)home_p;
    const uint64_t target = swar_bcast8(tag_of_hash(h));
    uint64_t i = (home_p + 1) & mask;
    for (uint64_t probed = 0; probed <= mask; probed += 8) {
      uint64_t g;
      std::memcpy(&g, &tags[i], 8);  // mirror bytes make the top wrap safe
      uint64_t match = swar_zero_bytes(g ^ target);
      const uint64_t empty = swar_zero_bytes(g);
      if (empty) {
        // lanes at or past the first empty slot are beyond the probe
        // chain's end — a match there belongs to some other home's chain
        const int first_empty_lane = __builtin_ctzll(empty) >> 3;
        match &= ((uint64_t)1 << (8 * first_empty_lane)) - 1;
      }
      while (match) {
        const uint64_t p = (i + (uint64_t)(__builtin_ctzll(match) >> 3)) & mask;
        if (table[p].sign == sign) return (int64_t)p;
        match &= match - 1;  // clear this lane's 0x80 marker
      }
      if (empty) return -1;
      i = (i + 8) & mask;
    }
    return -1;
  }

  int64_t find_pos(uint64_t sign) const {
    return probe_mode ? find_pos_simd_h(sign, splitmix64(sign))
                      : find_pos_scalar(sign);
  }

  void lru_unlink(int64_t r) {
    const Link l = lru[r];
    if (l.prev >= 0) lru[l.prev].next = l.next; else lru_head = l.next;
    if (l.next >= 0) lru[l.next].prev = l.prev; else lru_tail = l.prev;
    lru[r] = Link{-1, -1};
  }

  void lru_push_front(int64_t r) {
    lru[r] = Link{-1, lru_head};
    if (lru_head >= 0) lru[lru_head].prev = r;
    lru_head = r;
    if (lru_tail < 0) lru_tail = r;
  }

  void touch(int64_t r) {
    if (lru_head == r) return;
    lru_unlink(r);
    lru_push_front(r);
  }

  void erase_table_pos(uint64_t i) {
    uint64_t j = i;
    for (;;) {
      table[i].row = -1;
      tag_set(i, 0);
      uint64_t k;
      for (;;) {
        j = (j + 1) & mask;
        if (table[j].row < 0) return;
        k = home(table[j].sign);
        bool home_in_range = (i <= j) ? (i < k && k <= j) : (i < k || k <= j);
        if (!home_in_range) break;
      }
      table[i] = table[j];
      tag_set(i, tags[j]);
      i = j;
    }
  }

  // evict the LRU row; returns (row) and writes its sign to *sign_out
  int64_t evict_lru(uint64_t* sign_out) {
    const int64_t r = lru_tail;
    *sign_out = row_sign[r];
    const int64_t pos = find_pos(row_sign[r]);
    if (pos >= 0) erase_table_pos((uint64_t)pos);
    lru_unlink(r);
    --count;
    return r;
  }

  int64_t insert(uint64_t sign) {  // caller guarantees a free row exists
    const int64_t r = free_rows.back();
    free_rows.pop_back();
    row_sign[r] = sign;
    const uint64_t h = splitmix64(sign);
    uint64_t i = h & mask;
    while (table[i].row >= 0) i = (i + 1) & mask;
    table[i] = Slot{sign, r};
    tag_set(i, tag_of_hash(h));
    lru_push_front(r);
    ++count;
    return r;
  }

  // full reset (the drain paths): empty table + tags + LRU + free list
  void reset_directory() {
    std::fill(table.begin(), table.end(), Slot{0, -1});
    std::fill(tags.begin(), tags.end(), 0);
    std::fill(lru.begin(), lru.end(), Link{-1, -1});
    lru_head = lru_tail = -1;
    count = 0;
    free_rows.clear();
    for (int64_t r = capacity - 1; r >= 0; --r) free_rows.push_back(r);
  }

  // batch-local scratch for cache_admit_positions (reused across calls):
  // 16-byte bucket = sign + (epoch<<32 | int32 val), so a probe costs one
  // cache-line fetch and there is NO per-call table clear (the clear cost
  // a multi-MB memset every batch) — a bucket is live only when its u32
  // epoch stamp matches the current call (wrap needs 2^32 calls)
  struct ScratchSlot { uint64_t sign; uint64_t packed; };
  std::vector<ScratchSlot> scratch;
  uint64_t scratch_mask = 0;
  uint64_t scratch_epoch = 0;

  void scratch_reserve(int64_t n) {
    uint64_t want = 16;
    while (want < (uint64_t)n * 2) want <<= 1;
    if (want > scratch.size()) {
      scratch.assign(want, ScratchSlot{0, 0});
      scratch_mask = want - 1;
      scratch_epoch = 0;
    }
    ++scratch_epoch;
  }
};

}  // namespace

extern "C" {

void* cache_create(int64_t capacity) { return new Cache(capacity); }

void cache_destroy(void* h) { delete static_cast<Cache*>(h); }

int64_t cache_len(void* h) { return static_cast<Cache*>(h)->count; }

int64_t cache_capacity(void* h) { return static_cast<Cache*>(h)->capacity; }

// Admit a batch of DEDUPLICATED signs. Two passes:
//   pass 1: every resident sign is LRU-touched (so no member of THIS batch
//           can be chosen as an eviction victim in pass 2 — a victim evicted
//           and re-missed in the same batch would check stale data out of
//           the PS while its fresh row is still riding the step's
//           write-back output);
//   pass 2: each miss evicts the LRU row if full, takes a row, and is
//           recorded in miss_idx_out; evictions are reported in
//           evict_*_out (evicted row == the reused row).
// All output arrays sized n by the caller. Returns n_miss (or -1 if
// n > capacity, which would force a batch member to evict another);
// *n_evict_out is the eviction count (n_evict <= n_miss). Signs must be
// distinct within one call (duplicates would double-admit).
int64_t cache_admit(void* h, const uint64_t* signs, int64_t n,
                    int64_t* rows_out, int64_t* miss_idx_out,
                    uint64_t* evict_signs_out, int64_t* evict_rows_out,
                    int64_t* n_evict_out) {
  Cache& c = *static_cast<Cache*>(h);
  *n_evict_out = 0;
  if (n > c.capacity) return -1;
  int64_t n_miss = 0, n_evict = 0;
  const int64_t PF = 16;  // software prefetch distance (latency-bound probes)
  for (int64_t i = 0; i < n; ++i) {
    if (i + PF < n) {
      const uint64_t hp = c.home(signs[i + PF]);
      __builtin_prefetch(&c.tags[hp]);
      __builtin_prefetch(&c.table[hp]);
    }
    const int64_t pos = c.find_pos(signs[i]);
    if (pos >= 0) {
      const int64_t r = c.table[pos].row;
      c.touch(r);
      rows_out[i] = r;
    } else if (!c.touch_admits(signs[i])) {
      rows_out[i] = c.capacity;  // bypass: pad row — zero fwd, grad dropped
    } else {
      rows_out[i] = -1;
      miss_idx_out[n_miss++] = i;
    }
  }
  for (int64_t m = 0; m < n_miss; ++m) {
    const int64_t i = miss_idx_out[m];
    if (c.count >= c.capacity) {
      uint64_t ev_sign;
      const int64_t ev_row = c.evict_lru(&ev_sign);
      evict_signs_out[n_evict] = ev_sign;
      evict_rows_out[n_evict] = ev_row;
      ++n_evict;
      c.free_rows.push_back(ev_row);
    }
    rows_out[i] = c.insert(signs[i]);
  }
  *n_evict_out = n_evict;
  return n_miss;
}

// Positions-level admit: like cache_admit but over a RAW (duplicated) sign
// stream — e.g. the concatenated (slot, batch) single-id matrix — with the
// dedup done here. One call replaces the per-slot dedup + cross-slot dedup +
// admit + per-position row LUT the Python tier used to run (the 1-core
// feeder's dominant prepare cost). Outputs:
//   rows_out[i]        (n,)  int32 cache row of position i
//   miss_signs_out     (<=n) first-seen-order distinct missing signs
//   miss_rows_out      (<=n) the row each miss was assigned
//   evict_*_out        (<=n) write-back victims
//   n_unique_out       distinct signs in the batch
//   n_evict_out        eviction count
// Returns n_miss, or -1 if the batch's distinct count exceeds capacity
// (outputs are then undefined; no rows were admitted or evicted, though
// resident signs seen before the overflow was detected keep their LRU
// touch — harmless, the caller raises).
int64_t cache_admit_positions(void* h, const uint64_t* signs, int64_t n,
                              int32_t* rows_out,
                              uint64_t* miss_signs_out, int64_t* miss_rows_out,
                              uint64_t* evict_signs_out, int64_t* evict_rows_out,
                              int64_t* n_unique_out, int64_t* n_evict_out) {
  Cache& c = *static_cast<Cache*>(h);
  *n_evict_out = 0;
  c.scratch_reserve(n);
  // pass 1: dedup + touch residents; misses get ordinal placeholders.
  // A scratch bucket's val holds: row (>=0, resident seen this batch — or
  // the pad row c.capacity for a touch-gated bypass) or -(miss_ordinal+2)
  // for a pending miss; a bucket is live only when its epoch stamp
  // matches this call.
  const uint64_t ep = c.scratch_epoch & 0xffffffffULL;
  int64_t n_unique = 0, n_miss = 0;
  const int64_t PF = 16;  // software prefetch distance: the scratch and
  // main tables span tens of MB, so every probe is a DRAM-latency random
  // access — prefetching the home buckets of signs[i+16] overlaps ~16
  // outstanding misses and is the main single-core speedup here
  for (int64_t i = 0; i < n; ++i) {
    if (i + PF < n) {
      const uint64_t hp = splitmix64(signs[i + PF]);
      __builtin_prefetch(&c.scratch[c.scratch_mask & hp]);
      __builtin_prefetch(&c.tags[hp & c.mask]);
      __builtin_prefetch(&c.table[hp & c.mask]);
    }
    const uint64_t s = signs[i];
    uint64_t j = c.scratch_mask & splitmix64(s);
    int64_t v;
    for (;;) {
      const Cache::ScratchSlot& sl = c.scratch[j];
      if ((sl.packed >> 32) != ep) { v = -1; break; }  // empty this batch
      if (sl.sign == s) { v = (int32_t)(uint32_t)sl.packed; break; }
      j = (j + 1) & c.scratch_mask;
    }
    if (v == -1) {  // first time this batch
      ++n_unique;
      const int64_t pos = c.find_pos(s);
      if (pos >= 0) {
        const int64_t r = c.table[pos].row;
        c.touch(r);
        v = r;
      } else if (!c.touch_admits(s)) {
        v = c.capacity;  // bypass: pad row — zero fwd, grad dropped
      } else {
        miss_signs_out[n_miss] = s;
        v = -(n_miss + 2);
        ++n_miss;
      }
      c.scratch[j] = Cache::ScratchSlot{s, (ep << 32) | (uint32_t)(int32_t)v};
    }
    rows_out[i] = (int32_t)v;  // miss placeholders fixed in pass 3
  }
  if (n_unique > c.capacity) {
    // nothing admitted yet (only LRU touches happened) — safe to bail
    return -1;
  }
  // pass 2: assign rows to misses (evicting LRU residents not in this batch)
  int64_t n_evict = 0;
  for (int64_t m = 0; m < n_miss; ++m) {
    if (c.count >= c.capacity) {
      uint64_t ev_sign;
      const int64_t ev_row = c.evict_lru(&ev_sign);
      evict_signs_out[n_evict] = ev_sign;
      evict_rows_out[n_evict] = ev_row;
      ++n_evict;
      c.free_rows.push_back(ev_row);
    }
    miss_rows_out[m] = c.insert(miss_signs_out[m]);
  }
  // pass 3: resolve miss placeholders to their assigned rows
  for (int64_t i = 0; i < n; ++i) {
    const int32_t v = rows_out[i];
    if (v < 0) rows_out[i] = (int32_t)miss_rows_out[-(int64_t)v - 2];
  }
  *n_unique_out = n_unique;
  *n_evict_out = n_evict;
  return n_miss;
}

// Read-only probe (no admit, no LRU touch): rows_out[i] = row or -1.
void cache_probe(void* h, const uint64_t* signs, int64_t n, int64_t* rows_out) {
  Cache& c = *static_cast<Cache*>(h);
  for (int64_t i = 0; i < n; ++i) {
    if (i + 16 < n) {
      const uint64_t hp = c.home(signs[i + 16]);
      __builtin_prefetch(&c.tags[hp]);
      __builtin_prefetch(&c.table[hp]);
    }
    const int64_t pos = c.find_pos(signs[i]);
    rows_out[i] = pos >= 0 ? c.table[pos].row : -1;
  }
}

// Touch-gated admission knob (the reference's admit_probability analogue):
// a non-resident sign is admitted only on its t'th distinct-batch touch;
// earlier touches map to the pad row (zero forward, dropped gradient —
// the reference's non-admitted-sign semantics). t=1 restores exact
// admit-on-first-touch behavior.
void cache_set_admit_touches(void* h, int64_t t) {
  Cache& c = *static_cast<Cache*>(h);
  // counters are uint8: clamp to 255 so a huge threshold degrades to
  // "admit on the 255th touch" instead of wrapping and never admitting
  c.admit_touches = t < 1 ? 1 : (t > 255 ? 255 : t);
  if (c.admit_touches > 1) c.ensure_touch_table();
}

// Probe implementation switch: 0 = scalar (golden reference), nonzero =
// SIMD tag walk. Tags are maintained under both modes, so switching is
// always safe and results are bit-identical either way (the golden parity
// suite in tests/test_probe_layout.py is the enforcement).
void cache_set_probe_mode(void* h, int64_t mode) {
  static_cast<Cache*>(h)->probe_mode = mode ? 1 : 0;
}

int64_t cache_probe_mode(void* h) {
  return static_cast<Cache*>(h)->probe_mode;
}

// Non-destructive listing of every resident (sign, row) pair in LRU order
// (MRU first): the serving-freshness publish path reads resident rows
// without disturbing the directory.
int64_t cache_snapshot(void* h, uint64_t* signs_out, int64_t* rows_out) {
  Cache& c = *static_cast<Cache*>(h);
  int64_t k = 0;
  for (int64_t r = c.lru_head; r >= 0; r = c.lru[r].next) {
    signs_out[k] = c.row_sign[r];
    rows_out[k] = r;
    ++k;
  }
  return k;
}

// Drain every resident entry (for flush-all at checkpoint/eval boundaries):
// writes all (sign, row) pairs in LRU order (MRU first) and empties the
// directory. Returns the number drained.
int64_t cache_drain(void* h, uint64_t* signs_out, int64_t* rows_out) {
  Cache& c = *static_cast<Cache*>(h);
  int64_t k = 0;
  for (int64_t r = c.lru_head; r >= 0; r = c.lru[r].next) {
    signs_out[k] = c.row_sign[r];
    rows_out[k] = r;
    ++k;
  }
  c.reset_directory();
  return k;
}

// Seeded per-sign uniform embedding init, bit-identical to the Python
// golden model (persia_tpu/embedding/hashing.py uniform_init_for_signs:
// counter-mode splitmix64, top-53-bit mantissa, f64 affine then f32 cast).
// The cached tier inits every cold miss per step; doing it here keeps the
// single-core feeder off numpy's temporaries.
void cache_uniform_init(const uint64_t* signs, int64_t m, int64_t dim,
                        uint64_t seed, double lo, double hi, float* out) {
  const double kScale = 1.0 / 9007199254740992.0;  // 2^-53
  const double span = hi - lo;
  for (int64_t i = 0; i < m; ++i) {
    const uint64_t base = splitmix64(signs[i] ^ seed);
    float* row = out + i * dim;
    for (int64_t j = 0; j < dim; ++j) {
      const uint64_t s = splitmix64(base + (uint64_t)j);
      row[j] = (float)(lo + (double)(s >> 11) * kScale * span);
    }
  }
}

// Non-uniform seeded init for cached-tier cold misses. The algorithms are a
// verbatim mirror of native/ps.cpp Store::{normal,poisson,gamma}_from (each
// .cpp is a standalone translation unit by build design — _native_build
// compiles one source per .so — so the kernels are duplicated; the
// cross-backend golden tests in tests/test_init_methods.py pin all three
// implementations, Python included, to the same bits).
namespace initk {

constexpr double kToUnit = 1.0 / 9007199254740992.0;  // 2^-53
constexpr double kTwoPi = 6.283185307179586;

struct SubStream {
  uint64_t b;
  uint64_t j = 0;
  SubStream(uint64_t base, uint64_t i) : b(splitmix64(base + i)) {}
  double next() { return (double)(splitmix64(b + 1 + j++) >> 11) * kToUnit; }
};

inline double normal_from(SubStream& st, double mean, double std_) {
  double u1 = st.next();
  if (u1 < kToUnit) u1 = kToUnit;
  double u2 = st.next();
  return mean + std_ * (std::sqrt(-2.0 * std::log(u1)) * std::cos(kTwoPi * u2));
}

inline double poisson_from(SubStream& st, double lam) {
  if (lam <= 0.0) return 0.0;
  double big_l = std::exp(-lam);
  int k = 0;
  double p = 1.0;
  while (k < 4096) {
    ++k;
    p *= st.next();
    if (!(p > big_l)) break;
  }
  return (double)(k - 1);
}

inline double gamma_from(SubStream& st, double shape, double scale) {
  if (shape <= 0.0) return 0.0;
  double boost = 1.0, k = shape;
  if (k < 1.0) {
    double u = st.next();
    if (u < kToUnit) u = kToUnit;
    boost = std::pow(u, 1.0 / k);
    k += 1.0;
  }
  double d = k - 1.0 / 3.0;
  double c = 1.0 / (3.0 * std::sqrt(d));
  for (int it = 0; it < 1024; ++it) {
    double x = normal_from(st, 0.0, 1.0);
    double v = 1.0 + c * x;
    if (v <= 0.0) continue;
    v = v * v * v;
    double u = st.next();
    if (u < 1.0 - 0.0331 * x * x * x * x) return boost * d * v * scale;
    double lu = std::log(u < kToUnit ? kToUnit : u);
    if (lu < 0.5 * x * x + d * (1.0 - v + std::log(v)))
      return boost * d * v * scale;
  }
  return boost * d * scale;
}

}  // namespace initk

// kind codes: 0=uniform 1=gamma 2=poisson 3=normal 4=inverse_sqrt
// (config.py INIT_KIND_CODES)
void cache_init_rows(const uint64_t* signs, int64_t m, int64_t dim,
                     uint64_t seed, int kind, double p0, double p1,
                     float* out) {
  if (kind == 0) return cache_uniform_init(signs, m, dim, seed, p0, p1, out);
  if (kind == 4) {
    double b = 1.0 / std::sqrt((double)dim);
    return cache_uniform_init(signs, m, dim, seed, -b, b, out);
  }
  for (int64_t i = 0; i < m; ++i) {
    const uint64_t base = splitmix64(signs[i] ^ seed);
    float* row = out + i * dim;
    for (int64_t j = 0; j < dim; ++j) {
      initk::SubStream st(base, (uint64_t)j);
      double v = 0.0;
      if (kind == 3) v = initk::normal_from(st, p0, p1);
      else if (kind == 2) v = initk::poisson_from(st, p0);
      else if (kind == 1) v = initk::gamma_from(st, p0, p1);
      row[j] = (float)v;
    }
  }
}

// ------------------------------------------------------------ pending map
//
// sign → (token, src) open-addressing map for the stream's write-back
// hazard gate: which in-flight eviction payload (token = step seq) holds a
// sign's freshest row, and at which payload row (src). The Python gate
// previously re-scanned every pending record with a searchsorted per step
// (~45 ms/step at saturation on one core); this map makes the gate one
// native query. Insert overwrites (later steps win); remove is
// token-conditional so an in-flight flush cannot delete a newer step's
// entry for the same sign. Thread-safe via an internal mutex: the fused
// feeder entry point (cache_feed_batch) queries the ledger inside the
// admit call while the write-back thread removes landed entries, so the
// map can no longer rely on the stream's Python condvar alone.

struct PendingMap {
  struct Slot {
    uint64_t sign;
    int64_t src;
    uint32_t token;
    uint8_t state;  // 0 empty, 1 used, 2 tombstone
  };
  std::mutex mu;
  std::vector<Slot> t;
  uint64_t mask = 0;
  int64_t count = 0;      // used slots
  int64_t occupied = 0;   // used + tombstones (probe-chain load)

  void init(uint64_t cap) {
    uint64_t c = 64;
    while (c < cap) c <<= 1;
    t.assign(c, Slot{0, 0, 0, 0});
    mask = c - 1;
    count = occupied = 0;
  }

  void grow_if_needed(int64_t incoming) {
    if ((occupied + incoming) * 10 < (int64_t)t.size() * 7) return;
    std::vector<Slot> old;
    old.swap(t);
    uint64_t c = old.size();
    while ((count + incoming) * 10 >= (int64_t)c * 7) c <<= 1;
    t.assign(c, Slot{0, 0, 0, 0});
    mask = c - 1;
    count = occupied = 0;
    for (const Slot& s : old)
      if (s.state == 1) put(s.sign, s.src, s.token);
  }

  void put(uint64_t sign, int64_t src, uint32_t token) {
    uint64_t j = splitmix64(sign) & mask;
    int64_t first_tomb = -1;
    for (;;) {
      Slot& sl = t[j];
      if (sl.state == 0) {
        if (first_tomb >= 0) {
          Slot& ts = t[first_tomb];
          ts = Slot{sign, src, token, 1};
        } else {
          sl = Slot{sign, src, token, 1};
          ++occupied;
        }
        ++count;
        return;
      }
      if (sl.state == 2) {
        if (first_tomb < 0) first_tomb = (int64_t)j;
      } else if (sl.sign == sign) {
        sl.src = src;
        sl.token = token;  // overwrite: later steps win
        return;
      }
      j = (j + 1) & mask;
    }
  }

  // caller holds mu; returns true on a live hit
  inline bool find(uint64_t s, int64_t* src, uint32_t* token) const {
    uint64_t j = splitmix64(s) & mask;
    for (;;) {
      const Slot& sl = t[j];
      if (sl.state == 0) return false;
      if (sl.state == 1 && sl.sign == s) {
        *src = sl.src;
        *token = sl.token;
        return true;
      }
      j = (j + 1) & mask;
    }
  }
};

}  // namespace

extern "C" {

void* pending_map_create() {
  auto* m = new (std::nothrow) PendingMap();
  if (m) m->init(1 << 12);
  return m;
}

void pending_map_destroy(void* h) { delete static_cast<PendingMap*>(h); }

int64_t pending_map_size(void* h) {
  PendingMap& m = *static_cast<PendingMap*>(h);
  std::lock_guard<std::mutex> lk(m.mu);
  return m.count;
}

void pending_map_insert(void* h, const uint64_t* signs, const int64_t* srcs,
                        int64_t n, uint32_t token) {
  PendingMap& m = *static_cast<PendingMap*>(h);
  std::lock_guard<std::mutex> lk(m.mu);
  m.grow_if_needed(n);
  for (int64_t i = 0; i < n; ++i) m.put(signs[i], srcs[i], token);
}

// insert signs[i] -> (base_src + i, token): the per-step eviction span is
// always a contiguous ring region, so the feeder needs no host-side arange
// temporary to record it.
void pending_map_insert_range(void* h, const uint64_t* signs, int64_t n,
                              int64_t base_src, uint32_t token) {
  PendingMap& m = *static_cast<PendingMap*>(h);
  std::lock_guard<std::mutex> lk(m.mu);
  m.grow_if_needed(n);
  for (int64_t i = 0; i < n; ++i) m.put(signs[i], base_src + i, token);
}

// tokens_out/srcs_out filled per sign; src -1 = not pending. Returns hits.
int64_t pending_map_query(void* h, const uint64_t* signs, int64_t n,
                          uint32_t* tokens_out, int64_t* srcs_out) {
  PendingMap& m = *static_cast<PendingMap*>(h);
  std::lock_guard<std::mutex> lk(m.mu);
  int64_t hits = 0;
  const int64_t PF = 16;
  for (int64_t i = 0; i < n; ++i) {
    if (i + PF < n)
      __builtin_prefetch(&m.t[splitmix64(signs[i + PF]) & m.mask]);
    const uint64_t s = signs[i];
    srcs_out[i] = -1;
    tokens_out[i] = 0;
    int64_t src;
    uint32_t token;
    if (m.find(s, &src, &token)) {
      srcs_out[i] = src;
      tokens_out[i] = token;
      ++hits;
    }
  }
  return hits;
}

// remove signs whose CURRENT entry carries `token` (a later re-evict of the
// same sign under a newer token must survive its older flush)
void pending_map_remove(void* h, const uint64_t* signs, int64_t n,
                        uint32_t token) {
  PendingMap& m = *static_cast<PendingMap*>(h);
  std::lock_guard<std::mutex> lk(m.mu);
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t s = signs[i];
    uint64_t j = splitmix64(s) & m.mask;
    for (;;) {
      PendingMap::Slot& sl = m.t[j];
      if (sl.state == 0) break;
      if (sl.state == 1 && sl.sign == s) {
        if (sl.token == token) {
          sl.state = 2;  // tombstone (occupied stays; grow compacts)
          --m.count;
        }
        break;
      }
      j = (j + 1) & m.mask;
    }
  }
}

// ------------------------------------------------------------ fused feeder
//
// One call for the feeder hot loop's whole admit stage: dedup + admit +
// eviction-row selection + per-position LUT fill (cache_admit_positions)
// FUSED with the write-back hazard-ledger probe of the resulting misses.
// The Python orchestration this replaces ran two ctypes round-trips plus a
// full-width numpy query/nonzero per step under the stream lock; here the
// ledger is consulted inline, under its own mutex, only for the misses,
// and only the hits are materialized (compacted restore_{src,pos} pairs).
//
// Outputs (all sized by the caller as for cache_admit_positions):
//   restore_src_out[j]  ring row holding miss j's freshest entry
//   restore_pos_out[j]  ordinal into miss_signs_out/miss_rows_out
//   *n_restore_out      number of ledger hits among the misses
// Returns n_miss (or -1 on capacity overflow, same contract as
// cache_admit_positions; no ledger probe happens in that case).
//
// Ordering caveat (documented for the caller): the ledger probe here runs
// BEFORE the caller reserves this step's eviction-ring span, so a flush
// landing between this call and the reservation can free a referenced
// span for reuse by THIS step. The Python side therefore revalidates the
// (few) restore hits against the ledger again after the reservation; a
// hit that died in between simply rides the ordinary PS-probe path (its
// write-back has landed, so the PS copy is fresh).
// `salt` namespaces the ledger keys per cache group (key = sign ^ salt):
// the map is global to the stream but the gate is per-group, and with
// feature_index_prefix_bit=0 two groups can carry the same raw sign — an
// unsalted probe would resolve the OTHER group's in-flight ring rows.
// Must match the Python side's PendingSignMap salting exactly.
int64_t cache_feed_batch(void* h, void* pending_h,
                         const uint64_t* signs, int64_t n,
                         int32_t* rows_out,
                         uint64_t* miss_signs_out, int64_t* miss_rows_out,
                         uint64_t* evict_signs_out, int64_t* evict_rows_out,
                         int64_t* n_unique_out, int64_t* n_evict_out,
                         int64_t* restore_src_out, int64_t* restore_pos_out,
                         int64_t* n_restore_out, uint64_t salt) {
  *n_restore_out = 0;
  const int64_t n_miss = cache_admit_positions(
      h, signs, n, rows_out, miss_signs_out, miss_rows_out,
      evict_signs_out, evict_rows_out, n_unique_out, n_evict_out);
  if (n_miss < 0 || pending_h == nullptr) return n_miss;
  PendingMap& m = *static_cast<PendingMap*>(pending_h);
  std::lock_guard<std::mutex> lk(m.mu);
  if (m.count == 0) return n_miss;
  int64_t n_restore = 0;
  const int64_t PF = 16;
  for (int64_t j = 0; j < n_miss; ++j) {
    if (j + PF < n_miss)
      __builtin_prefetch(
          &m.t[splitmix64(miss_signs_out[j + PF] ^ salt) & m.mask]);
    int64_t src;
    uint32_t token;
    if (m.find(miss_signs_out[j] ^ salt, &src, &token)) {
      restore_src_out[n_restore] = src;
      restore_pos_out[n_restore] = j;
      ++n_restore;
    }
  }
  *n_restore_out = n_restore;
  return n_miss;
}

}  // extern "C"

// -------------------------------------------------------- access sketch
//
// Per-slot frequency / working-set sketch for the auto-tiering profiler
// (persia_tpu/embedding/tiering/). The feeder already walks every sign of
// every batch through cache_feed_batch, so this piggybacks on that stream:
// one sketch_observe call per group per step, attributing positions to
// slots by stride (the single-id fast path feeds a (S, B) prefixed sign
// matrix flattened row-major, so position i belongs to slot i / B).
//
// Three estimators, all O(1) per sign:
//   - a SHARED count-min (depth x width u32, the slot index mixed into the
//     key so identical raw signs in different slots don't collide) gives
//     per-sign frequency estimates;
//   - per-slot decayed totals (double) give the access mass;
//   - per-slot two-window linear-counting bitmaps give a decayed
//     distinct-sign (working set) estimate: observes set bits in the
//     CURRENT window, a decay swaps windows, and the estimate reads the
//     UNION of both — a sliding working set over the last two decay
//     periods, immune to the reset cliff a single bitmap would have;
//   - a per-slot top-K heavy-hitter list (count-min estimates) gives the
//     hot-mass fraction the planner uses to separate "skewed, cacheable"
//     from "uniform, stream-through" slots.
//
// Everything is guarded by one mutex: observe runs on the feeder thread,
// decay/stats/export on the fence (main) thread. The export is a
// versioned, geometry-checked byte blob so the profiler state rides a
// jobstate snapshot and resumes bit-identically.

namespace {

constexpr uint64_t SK_MAGIC = 0x70736b3176ULL;  // "psk1v"
constexpr uint64_t SK_SLOT_MIX = 0x9E3779B97F4A7C15ULL;
constexpr uint64_t SK_BM_SEED = 0x5BF03635F0C59A1FULL;
constexpr uint64_t SK_SAMPLE_SEED = 0xD1B54A32D192ED03ULL;
constexpr int64_t SK_MAX_DEPTH = 8;
constexpr uint64_t SK_DEPTH_SEED[SK_MAX_DEPTH] = {
    0xA076D1F3E59B7C21ULL, 0x2545F4914F6CDD1DULL, 0xDE916ABCC965815BULL,
    0x8C5FB1B7D477F4C1ULL, 0x27D4EB2F165667C5ULL, 0x165667B19E3779F9ULL,
    0xC2B2AE3D27D4EB4FULL, 0x9E3779B185EBCA87ULL,
};

struct AccessSketch {
  std::mutex mu;
  int64_t n_slots = 0, depth = 0, width = 0, bitmap_bits = 0, topk = 0;
  uint64_t width_mask = 0;
  int64_t bm_words = 0;
  std::vector<uint32_t> cm;          // depth * width
  std::vector<double> totals;        // n_slots
  std::vector<uint64_t> bits_cur;    // n_slots * bm_words
  std::vector<uint64_t> bits_prev;   // n_slots * bm_words
  std::vector<uint64_t> top_sign;    // n_slots * topk
  std::vector<double> top_est;       // n_slots * topk
  // PERSIA_SKETCH_SAMPLE: observe only signs with hash%k == 0, every
  // increment scaled by k — totals/cm stay unbiased in expectation, the
  // unfused ServiceCtx observe walk costs 1/k of its DRAM traffic.
  int64_t sample_k = 1;

  // caller holds mu: weighted observe — one call with weight w leaves the
  // count-min rows, totals and bitmap in EXACTLY the state w unit observes
  // of the same (slot, sign) would (saturating adds commute; the bitmap
  // bit is idempotent). The fused feeder walk uses this to observe each
  // distinct (slot, sign) of a batch once with its occurrence count.
  inline uint32_t observe_w(int64_t slot, uint64_t sign, uint64_t w) {
    const uint64_t key = sign ^ ((uint64_t)slot * SK_SLOT_MIX);
    uint32_t est = UINT32_MAX;
    for (int64_t d = 0; d < depth; ++d) {
      const uint64_t idx = splitmix64(key ^ SK_DEPTH_SEED[d]) & width_mask;
      uint32_t& c = cm[(size_t)(d * width + (int64_t)idx)];
      const uint64_t nv = (uint64_t)c + w;
      c = nv > (uint64_t)UINT32_MAX ? UINT32_MAX : (uint32_t)nv;
      if (c < est) est = c;
    }
    totals[(size_t)slot] += (double)w;
    const uint64_t b = splitmix64(key ^ SK_BM_SEED) % (uint64_t)bitmap_bits;
    bits_cur[(size_t)(slot * bm_words + (int64_t)(b >> 6))] |=
        (uint64_t)1 << (b & 63);
    return est;
  }

  // caller holds mu
  inline uint32_t observe_one(int64_t slot, uint64_t sign) {
    return observe_w(slot, sign, 1);
  }

  // caller holds mu: keep the slot's top-K heavy hitters by cm estimate
  inline void maybe_top(int64_t slot, uint64_t sign, uint32_t est) {
    double* e = &top_est[(size_t)(slot * topk)];
    uint64_t* s = &top_sign[(size_t)(slot * topk)];
    int64_t min_i = 0;
    for (int64_t k = 0; k < topk; ++k) {
      if (s[k] == sign && e[k] > 0.0) {
        if ((double)est > e[k]) e[k] = (double)est;
        return;
      }
      if (e[k] < e[min_i]) min_i = k;
    }
    if ((double)est > e[min_i]) {
      s[min_i] = sign;
      e[min_i] = (double)est;
    }
  }
};

}  // namespace

extern "C" {

// width_log2: log2 of the count-min row width; depth in [1, 8];
// bitmap_bits is rounded up to a multiple of 64; topk >= 1.
void* sketch_create(int64_t n_slots, int64_t width_log2, int64_t depth,
                    int64_t bitmap_bits, int64_t topk) {
  if (n_slots <= 0 || width_log2 < 4 || width_log2 > 28 || depth < 1 ||
      depth > SK_MAX_DEPTH || bitmap_bits < 64 || topk < 1)
    return nullptr;
  auto* sk = new (std::nothrow) AccessSketch();
  if (!sk) return nullptr;
  sk->n_slots = n_slots;
  sk->depth = depth;
  sk->width = (int64_t)1 << width_log2;
  sk->width_mask = (uint64_t)(sk->width - 1);
  sk->bitmap_bits = (bitmap_bits + 63) & ~(int64_t)63;
  sk->bm_words = sk->bitmap_bits >> 6;
  sk->topk = topk;
  sk->cm.assign((size_t)(sk->depth * sk->width), 0);
  sk->totals.assign((size_t)n_slots, 0.0);
  sk->bits_cur.assign((size_t)(n_slots * sk->bm_words), 0);
  sk->bits_prev.assign((size_t)(n_slots * sk->bm_words), 0);
  sk->top_sign.assign((size_t)(n_slots * topk), 0);
  sk->top_est.assign((size_t)(n_slots * topk), 0.0);
  return sk;
}

void sketch_destroy(void* h) { delete static_cast<AccessSketch*>(h); }

int64_t sketch_n_slots(void* h) {
  return static_cast<AccessSketch*>(h)->n_slots;
}

// Strided attribution: position i belongs to slot_base + i/samples_per_slot
// (the feeder's flattened (S, B) group matrix); samples_per_slot <= 0 sends
// every sign to slot_base (the general path's per-slot calls). Signs
// falling past n_slots are dropped (defensive — the Python side sizes the
// call). Returns the number of signs observed.
int64_t sketch_observe(void* h, const uint64_t* signs, int64_t n,
                       int64_t samples_per_slot, int64_t slot_base) {
  AccessSketch& sk = *static_cast<AccessSketch*>(h);
  std::lock_guard<std::mutex> lk(sk.mu);
  int64_t seen = 0;
  const uint64_t k = (uint64_t)sk.sample_k;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t slot =
        slot_base + (samples_per_slot > 0 ? i / samples_per_slot : 0);
    if (slot < 0 || slot >= sk.n_slots) continue;
    if (k > 1 && splitmix64(signs[i] ^ SK_SAMPLE_SEED) % k != 0) {
      ++seen;  // sampled away, not dropped: the caller sized the call
      continue;
    }
    const uint32_t est = sk.observe_w(slot, signs[i], k);
    sk.maybe_top(slot, signs[i], est);
    ++seen;
  }
  return seen;
}

// 1-in-k observe sampling (PERSIA_SKETCH_SAMPLE): the sign-hash gate keeps
// the sample set consistent across batches (a kept sign is always kept, so
// per-sign frequency estimates stay exact * k), increments are scaled by k
// so totals/cm stay unbiased, and slot_stats scales the linear-counting
// unique estimate back up by k (only 1/k of distinct signs reach the
// bitmap). k <= 1 disables sampling.
void sketch_set_sample(void* h, int64_t k) {
  AccessSketch& sk = *static_cast<AccessSketch*>(h);
  std::lock_guard<std::mutex> lk(sk.mu);
  sk.sample_k = k < 1 ? 1 : (k > (1 << 20) ? (1 << 20) : k);
}

// Exponential decay: scales the count-min counters, per-slot totals and
// heavy-hitter estimates by `factor` (clamped to [0, 1]) and slides the
// working-set window (prev = cur, cur cleared). Called at fences.
void sketch_decay(void* h, double factor) {
  AccessSketch& sk = *static_cast<AccessSketch*>(h);
  std::lock_guard<std::mutex> lk(sk.mu);
  if (factor < 0.0) factor = 0.0;
  if (factor > 1.0) factor = 1.0;
  for (auto& c : sk.cm) c = (uint32_t)((double)c * factor);
  for (auto& t : sk.totals) t *= factor;
  for (auto& e : sk.top_est) e *= factor;
  sk.bits_prev = sk.bits_cur;
  std::fill(sk.bits_cur.begin(), sk.bits_cur.end(), 0);
}

// out[0] = decayed access total, out[1] = distinct-sign (working set)
// estimate over the union of both windows (linear counting),
// out[2] = hot-mass fraction (top-K estimate mass / total),
// out[3] = top-1 fraction. Returns 0, or -1 on a bad slot index.
int64_t sketch_slot_stats(void* h, int64_t slot, double* out) {
  AccessSketch& sk = *static_cast<AccessSketch*>(h);
  std::lock_guard<std::mutex> lk(sk.mu);
  if (slot < 0 || slot >= sk.n_slots) return -1;
  int64_t ones = 0;
  const uint64_t* c = &sk.bits_cur[(size_t)(slot * sk.bm_words)];
  const uint64_t* p = &sk.bits_prev[(size_t)(slot * sk.bm_words)];
  for (int64_t w = 0; w < sk.bm_words; ++w)
    ones += __builtin_popcountll(c[w] | p[w]);
  const double m = (double)sk.bitmap_bits;
  const int64_t zeros = sk.bitmap_bits - ones;
  double unique = zeros == 0 ? m : m * std::log(m / (double)zeros);
  // under 1-in-k sampling only ~unique/k distinct signs reach the bitmap
  if (sk.sample_k > 1) unique *= (double)sk.sample_k;
  const double total = sk.totals[(size_t)slot];
  double hot = 0.0, top1 = 0.0;
  const double* e = &sk.top_est[(size_t)(slot * sk.topk)];
  for (int64_t k = 0; k < sk.topk; ++k) {
    hot += e[k];
    if (e[k] > top1) top1 = e[k];
  }
  out[0] = total;
  out[1] = unique;
  out[2] = total > 0.0 ? std::min(1.0, hot / total) : 0.0;
  out[3] = total > 0.0 ? std::min(1.0, top1 / total) : 0.0;
  return 0;
}

// Count-min point estimate for (slot, sign) — test/introspection surface.
double sketch_estimate(void* h, int64_t slot, uint64_t sign) {
  AccessSketch& sk = *static_cast<AccessSketch*>(h);
  std::lock_guard<std::mutex> lk(sk.mu);
  if (slot < 0 || slot >= sk.n_slots) return -1.0;
  const uint64_t key = sign ^ ((uint64_t)slot * SK_SLOT_MIX);
  uint32_t est = UINT32_MAX;
  for (int64_t d = 0; d < sk.depth; ++d) {
    const uint64_t idx = splitmix64(key ^ SK_DEPTH_SEED[d]) & sk.width_mask;
    const uint32_t v = sk.cm[(size_t)(d * sk.width + (int64_t)idx)];
    if (v < est) est = v;
  }
  return (double)est;
}

int64_t sketch_export_size(void* h) {
  AccessSketch& sk = *static_cast<AccessSketch*>(h);
  std::lock_guard<std::mutex> lk(sk.mu);
  return (int64_t)(sizeof(uint64_t) * 7 + sk.cm.size() * sizeof(uint32_t) +
                   sk.totals.size() * sizeof(double) +
                   (sk.bits_cur.size() + sk.bits_prev.size() +
                    sk.top_sign.size()) * sizeof(uint64_t) +
                   sk.top_est.size() * sizeof(double));
}

// Versioned byte blob: magic + geometry header, then the raw arrays.
// Returns bytes written, or -1 when cap is too small.
int64_t sketch_export(void* h, uint8_t* out, int64_t cap) {
  AccessSketch& sk = *static_cast<AccessSketch*>(h);
  std::lock_guard<std::mutex> lk(sk.mu);
  const uint64_t hdr[7] = {SK_MAGIC, 1,
                           (uint64_t)sk.n_slots, (uint64_t)sk.depth,
                           (uint64_t)sk.width, (uint64_t)sk.bitmap_bits,
                           (uint64_t)sk.topk};
  int64_t need = (int64_t)sizeof(hdr);
  need += (int64_t)(sk.cm.size() * sizeof(uint32_t));
  need += (int64_t)(sk.totals.size() * sizeof(double));
  need += (int64_t)((sk.bits_cur.size() + sk.bits_prev.size() +
                     sk.top_sign.size()) * sizeof(uint64_t));
  need += (int64_t)(sk.top_est.size() * sizeof(double));
  if (cap < need) return -1;
  uint8_t* q = out;
  auto put = [&q](const void* src, size_t nb) {
    __builtin_memcpy(q, src, nb);
    q += nb;
  };
  put(hdr, sizeof(hdr));
  put(sk.cm.data(), sk.cm.size() * sizeof(uint32_t));
  put(sk.totals.data(), sk.totals.size() * sizeof(double));
  put(sk.bits_cur.data(), sk.bits_cur.size() * sizeof(uint64_t));
  put(sk.bits_prev.data(), sk.bits_prev.size() * sizeof(uint64_t));
  put(sk.top_sign.data(), sk.top_sign.size() * sizeof(uint64_t));
  put(sk.top_est.data(), sk.top_est.size() * sizeof(double));
  return (int64_t)(q - out);
}

// Geometry must match the receiving sketch exactly (the profiler
// re-creates it from the same config before importing). Returns 0, or -1
// on a short/mismatched blob.
int64_t sketch_import(void* h, const uint8_t* data, int64_t n) {
  AccessSketch& sk = *static_cast<AccessSketch*>(h);
  std::lock_guard<std::mutex> lk(sk.mu);
  uint64_t hdr[7];
  if (n < (int64_t)sizeof(hdr)) return -1;
  __builtin_memcpy(hdr, data, sizeof(hdr));
  if (hdr[0] != SK_MAGIC || hdr[1] != 1 || hdr[2] != (uint64_t)sk.n_slots ||
      hdr[3] != (uint64_t)sk.depth || hdr[4] != (uint64_t)sk.width ||
      hdr[5] != (uint64_t)sk.bitmap_bits || hdr[6] != (uint64_t)sk.topk)
    return -1;
  const uint8_t* q = data + sizeof(hdr);
  int64_t left = n - (int64_t)sizeof(hdr);
  auto take = [&q, &left](void* dst, size_t nb) -> bool {
    if (left < (int64_t)nb) return false;
    __builtin_memcpy(dst, q, nb);
    q += nb;
    left -= (int64_t)nb;
    return true;
  };
  if (!take(sk.cm.data(), sk.cm.size() * sizeof(uint32_t))) return -1;
  if (!take(sk.totals.data(), sk.totals.size() * sizeof(double))) return -1;
  if (!take(sk.bits_cur.data(), sk.bits_cur.size() * sizeof(uint64_t)))
    return -1;
  if (!take(sk.bits_prev.data(), sk.bits_prev.size() * sizeof(uint64_t)))
    return -1;
  if (!take(sk.top_sign.data(), sk.top_sign.size() * sizeof(uint64_t)))
    return -1;
  if (!take(sk.top_est.data(), sk.top_est.size() * sizeof(double))) return -1;
  return 0;
}

}  // extern "C"

// ------------------------------------------------------------ sharded feeder
//
// ISSUE 14: the admit directory + LRU partitioned into S independent shards
// keyed by the per-group salted sign hash (the pending-ledger salt from the
// fused-feed PR doubles as the partition key), each with its own mutex, LRU
// chain and row-range of the device slab. cache_feed_batch_sharded buckets
// the raw position stream by shard, runs the admit/evict/row-LUT walk one
// shard per pool thread (software-prefetch pipelining preserved per shard)
// and FUSES the tiering sketch observe into the same walk: each position
// bumps a shard-local (slot, sign) occurrence scratch, and after the admit
// walk every distinct pair is observed ONCE into the shard's private
// sub-sketch with its occurrence count as the weight (observe_w above) —
// the sign matrix is traversed once instead of twice, and the dominant
// count-min DRAM traffic shrinks by the batch's per-(slot, sign) dedup
// ratio even on one core.
//
// Determinism: the shard of a sign is a pure function of (sign, part_salt,
// S); the counting-sort bucketing is stable, so each shard walks its
// positions in input order against shard-private state; results are merged
// in ascending shard order on the calling thread. The emitted row LUT,
// miss list, eviction list, ledger-restore entries and sub-sketch states
// are therefore bit-identical at ANY thread count (threads only change
// which OS thread runs a shard's walk, never the walk itself) — pinned by
// tests/test_sharded_feeder.py. With S == 1 the walk degenerates to the
// legacy cache_feed_batch algorithm and its outputs match it bitwise.
//
// Locking (ranked in persia_tpu/analysis/lock_order.py): a walker thread
// holds its OWN shard's mu for the admit passes, releases it, then takes
// the sub-sketch mu (observe apply) and then the pending-ledger mu (miss
// probe). The three are never nested and no thread ever holds two shard
// mutexes, so the feeder adds leaf-level locks only. Concurrent
// cache_sharded_probe/len/snapshot calls serialize per shard on shard.mu;
// concurrent feed/drain calls on one handle are the caller's to serialize
// (the Python stream lock already does), matching the legacy contract.

namespace {

constexpr int64_t SHARD_MAX = 64;

inline int64_t shard_route(uint64_t sign, uint64_t part_salt,
                           int64_t n_shards) {
  // multiply-high range reduction of the salted sign hash: uniform for any
  // shard count, no modulo bias, and a pure function of (sign, salt, S) —
  // the partition never depends on thread count.
  return (int64_t)((unsigned __int128)splitmix64(sign ^ part_salt) *
                   (unsigned __int128)(uint64_t)n_shards >> 64);
}

struct FeedShard {
  Cache dir;      // shard-local directory; emitted rows offset by row_base
  std::mutex mu;  // guards dir: feed walk vs probe/drain/snapshot/len
  int64_t row_base = 0;
  // per-feed outputs, merged by the caller in ascending shard order
  std::vector<uint64_t> miss_signs;
  std::vector<int64_t> miss_rows;
  std::vector<uint64_t> ev_signs;
  std::vector<int64_t> ev_rows;
  std::vector<int64_t> rst_src;
  std::vector<int64_t> rst_pos;  // shard-local miss ordinals
  int64_t n_unique = 0;
  bool overflow = false;
  // last feed's walk time (both phases + observe + ledger probe), written
  // by whichever pool thread ran this shard; atomic so the stats thread
  // can read mid-feed
  std::atomic<int64_t> busy_ns{0};
  // last feed's scheduling wait: dispatch-to-walk-start ns summed over
  // both phases. busy says how long the shard's walk ran; stall says how
  // long the walk sat in the pool queue first — together they separate
  // "shard imbalance" from "not enough cores" on the gauge surface.
  std::atomic<int64_t> stall_ns{0};
  // fused observe scratch: occurrence counts + slot ids PARALLEL to the
  // admit scratch (indexed by the same bucket). The admit walk already
  // dedups the batch by sign, so when signs are slot-prefixed
  // (feature_index_prefix_bit > 0: sign -> slot is injective) the
  // (slot, sign) observe dedup rides the probe the admit walk has ALREADY
  // paid for — the fused observe adds one 4-byte counter bump per
  // position and a weighted sub-sketch observe per DISTINCT sign, never a
  // second hash-table walk over the sign matrix. The Python side only
  // passes sketches when the prefix invariant holds; without it the
  // unfused routed observe stays in charge.
  std::vector<uint32_t> obs_count;  // sized like Cache::scratch
  std::vector<uint32_t> obs_slot;   // UINT32_MAX = unattributed (skip)
  std::vector<uint32_t> obs_order;  // scratch indices, first-seen order
  // probe-wave compact observe stream (round 17): the wave detect already
  // knows each first-seen sign at the moment it enqueues the probe, so in
  // probe mode the (sign, slot, count) triples land in these first-seen-
  // order SoA vectors instead of being scattered across the scratch-sized
  // tables above — shard_observe_apply then STREAMS them linearly (zero
  // random reads) rather than chasing a random scratch + obs_slot line
  // per distinct sign. In this mode obs_count[j] holds the compact
  // ORDINAL (index into obs_cnt_c) so the duplicate bump stays one
  // already-prefetched random write plus one L1-resident increment, and
  // obs_slot/obs_order are not written at all. The scalar walk leaves
  // these empty (obs_reserve clears them), which is how
  // shard_observe_apply picks its path; the sketch sees the SAME
  // (slot, sign, weight) sequence either way — state stays bit-identical.
  std::vector<uint64_t> obs_sign_c;
  std::vector<uint32_t> obs_slot_c;
  std::vector<uint32_t> obs_cnt_c;

  explicit FeedShard(int64_t cap) : dir(cap) {}

  void obs_reserve(int64_t n) {
    if (obs_count.size() != dir.scratch.size()) {
      obs_count.assign(dir.scratch.size(), 0);
      obs_slot.assign(dir.scratch.size(), 0);
    }
    obs_order.clear();
    obs_order.reserve((size_t)n);
    obs_sign_c.clear();
    obs_slot_c.clear();
    obs_cnt_c.clear();
  }
};

struct ShardedCache {
  int64_t total_capacity = 0;
  int64_t n_shards = 1;
  uint64_t part_salt = 0;
  std::vector<std::unique_ptr<FeedShard>> shards;

  // calling-thread bucketing buffers (one feed in flight per handle at a
  // time — the caller serializes feed/drain, so these never race)
  std::vector<uint8_t> sid;
  std::vector<int64_t> start;  // CSR offsets, n_shards + 1
  std::vector<int64_t> fill;
  std::vector<int64_t> pos;    // position indices grouped by shard

  // persistent pool: n_threads - 1 workers + the calling thread. Every
  // dispatch is exactly n_shards items; that invariant makes the lock-free
  // item claim in drain_items safe (a stale wake can fetch-add past the
  // end but can never claim a live item of a later dispatch while an
  // earlier one is unfinished — the caller's items_done barrier forbids
  // replacing `job` while any invocation is in flight).
  std::mutex pool_mu;
  std::condition_variable cv_work, cv_done;
  uint64_t gen = 0;
  std::function<void(int64_t)> job;
  std::atomic<int64_t> next_item{0};
  int64_t items_done = 0;
  bool stopping = false;
  int64_t n_threads = 1;
  // walker pinning policy (PERSIA_FEED_AFFINITY): 0 = none, 1 = compact
  // (worker i -> cpu i % ncpu, packs walkers onto one socket for shared
  // LLC), 2 = spread (workers striped across the cpu range, one walker
  // per NUMA node's worth of cores). Guarded by pool_mu; changing it
  // respawns the workers so the pin applies from thread start.
  int64_t affinity_mode = 0;
  std::vector<std::thread> workers;

  ShardedCache(int64_t cap, int64_t n, uint64_t salt, int64_t threads)
      : total_capacity(cap), n_shards(n), part_salt(salt) {
    const int64_t base = cap / n, rem = cap % n;
    int64_t row_base = 0;
    for (int64_t s = 0; s < n; ++s) {
      const int64_t c = base + (s < rem ? 1 : 0);
      shards.emplace_back(new FeedShard(c));
      shards.back()->row_base = row_base;
      row_base += c;
    }
    set_threads(threads);
  }

  ~ShardedCache() { set_threads(1); }

  void set_threads(int64_t t) {
    if (t < 1) t = 1;
    if (t > n_shards) t = n_shards;  // >S threads would only idle
    {
      std::lock_guard<std::mutex> lk(pool_mu);
      if (t == n_threads) return;
      stopping = true;
    }
    cv_work.notify_all();
    for (auto& w : workers) w.join();
    workers.clear();
    {
      std::lock_guard<std::mutex> lk(pool_mu);
      stopping = false;
      n_threads = t;
    }
    for (int64_t i = 0; i < t - 1; ++i)
      workers.emplace_back([this, i] { worker_loop(i); });
  }

  void set_affinity(int64_t mode) {
    if (mode < 0 || mode > 2) mode = 0;
    int64_t t;
    {
      std::lock_guard<std::mutex> lk(pool_mu);
      if (mode == affinity_mode) return;
      affinity_mode = mode;
      t = n_threads;
      if (workers.empty()) return;  // pin applies when workers next spawn
      stopping = true;
    }
    // respawn so every worker re-reads the policy at thread start
    cv_work.notify_all();
    for (auto& w : workers) w.join();
    workers.clear();
    {
      std::lock_guard<std::mutex> lk(pool_mu);
      stopping = false;
    }
    for (int64_t i = 0; i < t - 1; ++i)
      workers.emplace_back([this, i] { worker_loop(i); });
  }

  // Best-effort CPU pin for pool worker widx, applied once at thread
  // start. The calling thread (which also walks shards) is never pinned —
  // the embedding tier owns its placement. No-op off Linux or when the
  // policy is 0.
  void apply_affinity(int64_t widx) {
#if defined(__linux__)
    int64_t mode, t;
    {
      std::lock_guard<std::mutex> lk(pool_mu);
      mode = affinity_mode;
      t = n_threads;
    }
    if (mode == 0) return;
    const long ncpu_l = sysconf(_SC_NPROCESSORS_ONLN);
    if (ncpu_l <= 0) return;
    const int64_t ncpu = (int64_t)ncpu_l;
    const int64_t n_workers = t > 1 ? t - 1 : 1;
    const int64_t cpu = mode == 1 ? widx % ncpu
                                  : (widx * ncpu / n_workers) % ncpu;
    cpu_set_t set;
    CPU_ZERO(&set);
    CPU_SET((int)cpu, &set);
    pthread_setaffinity_np(pthread_self(), sizeof(set), &set);
#else
    (void)widx;
#endif
  }

  void drain_items() {
    int64_t done = 0;
    for (;;) {
      const int64_t s = next_item.fetch_add(1);
      if (s >= n_shards) break;
      job(s);
      ++done;
    }
    if (done > 0) {
      std::lock_guard<std::mutex> lk(pool_mu);
      items_done += done;
      if (items_done >= n_shards) cv_done.notify_all();
    }
  }

  void worker_loop(int64_t widx) {
    apply_affinity(widx);
    uint64_t seen = 0;
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(pool_mu);
        cv_work.wait(lk, [&] { return stopping || gen != seen; });
        if (stopping) return;
        seen = gen;
      }
      drain_items();
    }
  }

  // run fn(s) for every shard (caller participates); returns only when all
  // n_shards items completed — the completion barrier that licenses
  // replacing `job` on the next dispatch.
  void run_shards(const std::function<void(int64_t)>& fn) {
    if (n_threads <= 1) {
      for (int64_t s = 0; s < n_shards; ++s) fn(s);
      return;
    }
    {
      std::lock_guard<std::mutex> lk(pool_mu);
      job = fn;
      items_done = 0;
      next_item.store(0);
      ++gen;
    }
    cv_work.notify_all();
    drain_items();
    std::unique_lock<std::mutex> lk(pool_mu);
    cv_done.wait(lk, [&] { return items_done >= n_shards; });
  }
};

// phase A for one shard (caller holds sh.mu): dedup + LRU-touch residents
// over the shard's slice of the position list; misses get ordinal
// placeholders. Scratch values are GLOBAL: row_base-offset rows, the
// global pad row (total_capacity) for touch-gated bypasses, or
// -(local_miss_ordinal + 2). Nothing is admitted yet, so a capacity
// overflow in any shard can still bail with only LRU touches applied —
// the cache_admit_positions contract. When observing, every position also
// bumps the (slot, sign) occurrence scratch: the fused single pass.
void shard_pass1(FeedShard& sh, const uint64_t* signs, int32_t* rows_out,
                 const int64_t* pos, int64_t p0, int64_t p1,
                 int64_t total_capacity, bool observing,
                 int64_t samples_per_slot, int64_t slot_base) {
  Cache& c = sh.dir;
  const int64_t n_local = p1 - p0;
  c.scratch_reserve(n_local);
  if (observing) sh.obs_reserve(n_local);
  sh.miss_signs.clear();
  sh.n_unique = 0;
  sh.overflow = false;
  const uint64_t ep = c.scratch_epoch & 0xffffffffULL;
  if (c.probe_mode) {
    // Probe-wave walk (round 17), three phases per wave of W positions:
    //
    //   stage   — hash the NEXT wave's signs and prefetch their scratch
    //             (and occurrence) lines one full wave ahead.
    //   detect  — walk the current wave in input order through the
    //             scratch dedup. Batch duplicates (the bulk of a zipf
    //             stream) finish here and touch NOTHING else; first-seen
    //             signs enqueue on a pending list, write a sentinel
    //             scratch entry, and prefetch ONLY THEN their tag-group
    //             and payload-home lines. The directory's random DRAM
    //             lines are fetched once per unique sign, not once per
    //             position (the scalar walk's fixed lookahead prefetches
    //             the payload line for every position, duplicates
    //             included — wasted line-fill-buffer slots that this
    //             shape gives back).
    //   resolve — run the pending probes back to back with the SIMD tag
    //             walk, their lines in flight since detect; patch the
    //             sentinel scratch entries, the row LUT, and the
    //             dup-of-pending fixups. The LRU splice is deferred to a
    //             wave-local buffer (node line prefetched at hit time)
    //             drained after the probe loop — the pointer-chasing
    //             unlink/push-front no longer sits between probes.
    //
    // Bit-identity with the scalar walk: the scratch chain is walked and
    // written in the same input order (sentinels occupy exactly the slots
    // the scalar's values would), find_pos and the dedup never read LRU
    // state, misses/touch_admits/obs_order happen in first-seen input
    // order, hits within one batch touch DISTINCT rows, and the touch
    // drain preserves input order — the LRU list after every wave is
    // identical, and pass 2 (the only LRU consumer) runs strictly after
    // pass 1. Sentinels (INT32_MIN + k, k < W) cannot collide with real
    // LUT values: rows/pad are >= 0 and miss placeholders are bounded by
    // -(batch + 2), far above INT32_MIN for any int32-addressable batch.
    constexpr int64_t W = 32;
    uint64_t h_a[W], h_b[W];
    uint64_t* h_cur = h_a;
    uint64_t* h_next = h_b;
    int64_t pend_i[W];   // position index of each first-seen sign
    uint64_t pend_s[W];  // its sign
    uint64_t pend_h[W];  // its splitmix64 hash
    uint64_t pend_j[W];  // its scratch slot (sentinel to patch)
    int64_t pend_v[W];   // resolved LUT value
    int64_t fix_i[W];    // positions that duped a still-pending sign
    int32_t fix_k[W];    // ... and which pending entry they duped
    int64_t touch_rows[W];
    const auto stage_wave = [&](int64_t w0, int64_t w1, uint64_t* hs) {
      for (int64_t t = w0; t < w1; ++t) {
        const uint64_t hp = splitmix64(signs[pos[t]]);
        hs[t - w0] = hp;
        __builtin_prefetch(&c.scratch[c.scratch_mask & hp]);
        if (observing) __builtin_prefetch(&sh.obs_count[c.scratch_mask & hp]);
      }
    };
    stage_wave(p0, std::min(p0 + W, p1), h_cur);
    for (int64_t w0 = p0; w0 < p1; w0 += W) {
      const int64_t w1 = std::min(w0 + W, p1);
      stage_wave(w1, std::min(w1 + W, p1), h_next);
      int64_t n_pend = 0, n_fix = 0, n_touch = 0;
      for (int64_t t = w0; t < w1; ++t) {  // detect
        const int64_t i = pos[t];
        const uint64_t s = signs[i];
        const uint64_t hp = h_cur[t - w0];
        uint64_t j = c.scratch_mask & hp;
        int64_t v;
        for (;;) {
          const Cache::ScratchSlot& sl = c.scratch[j];
          if ((sl.packed >> 32) != ep) { v = -1; break; }
          if (sl.sign == s) { v = (int32_t)(uint32_t)sl.packed; break; }
          j = (j + 1) & c.scratch_mask;
        }
        if (v == -1) {  // first time this batch: enqueue, probe later
          ++sh.n_unique;
          const int64_t k = n_pend++;
          pend_i[k] = i;
          pend_s[k] = s;
          pend_h[k] = hp;
          pend_j[k] = j;
          c.scratch[j] = Cache::ScratchSlot{
              s, (ep << 32) | (uint32_t)(int32_t)(INT32_MIN + k)};
          __builtin_prefetch(&c.tags[hp & c.mask]);
          __builtin_prefetch(&c.table[hp & c.mask]);
          if (observing) {  // compact stream: ordinal in obs_count[j]
            const int64_t slot =
                slot_base + (samples_per_slot > 0 ? i / samples_per_slot : 0);
            sh.obs_count[j] = (uint32_t)sh.obs_sign_c.size();
            sh.obs_sign_c.push_back(s);
            sh.obs_slot_c.push_back(slot < 0 ? UINT32_MAX : (uint32_t)slot);
            sh.obs_cnt_c.push_back(1);
          }
        } else {
          if (v <= INT32_MIN + (W - 1)) {  // duped a pending probe
            fix_i[n_fix] = i;
            fix_k[n_fix++] = (int32_t)(v - INT32_MIN);
          } else {
            rows_out[i] = (int32_t)v;
          }
          if (observing) ++sh.obs_cnt_c[sh.obs_count[j]];
        }
      }
      // resolve, two loops: the probes run back to back first, and each
      // outcome fires the NEXT dependent line's prefetch (hit -> its LRU
      // node, miss -> its admission-counter byte in the touch table — a
      // random DRAM line the scalar walk always eats cold) so loop two
      // finds every line it patches already in flight.
      int64_t hit_r[W];
      for (int64_t k = 0; k < n_pend; ++k) {
        const int64_t lpos = c.find_pos_simd_h(pend_s[k], pend_h[k]);
        hit_r[k] = lpos < 0 ? -1 : (int64_t)c.table[lpos].row;
        if (lpos >= 0) {
          __builtin_prefetch(&c.lru[hit_r[k]]);
        } else if (c.admit_touches > 1) {
          __builtin_prefetch(&c.touch_counts[c.touch_idx(pend_s[k])], 1);
        }
      }
      for (int64_t k = 0; k < n_pend; ++k) {  // patch (first-seen order)
        const uint64_t s = pend_s[k];
        int64_t v;
        if (hit_r[k] >= 0) {
          const int64_t r = hit_r[k];
          touch_rows[n_touch++] = r;  // LRU splice deferred past the wave
          v = sh.row_base + r;
        } else if (!c.touch_admits(s)) {
          v = total_capacity;  // global pad row: zero fwd, grad dropped
        } else {
          v = -((int64_t)sh.miss_signs.size() + 2);
          sh.miss_signs.push_back(s);
        }
        pend_v[k] = v;
        c.scratch[pend_j[k]].packed = (ep << 32) | (uint32_t)(int32_t)v;
        rows_out[pend_i[k]] = (int32_t)v;
      }
      for (int64_t f = 0; f < n_fix; ++f)
        rows_out[fix_i[f]] = (int32_t)pend_v[fix_k[f]];
      // two-phase touch drain: the unlink needs each node's NEIGHBOR
      // lines, a serial two-miss chain when done inline. Phase 1 reads
      // the (already-prefetched) nodes and fires their neighbors'
      // prefetches across the whole wave; phase 2 splices. Reads can go
      // stale between phases when touched rows neighbor each other —
      // harmless, prefetch is a hint and touch() re-reads live links.
      for (int64_t k = 0; k < n_touch; ++k) {
        const Cache::Link& nd = c.lru[touch_rows[k]];
        if (nd.prev >= 0) __builtin_prefetch(&c.lru[nd.prev]);
        if (nd.next >= 0) __builtin_prefetch(&c.lru[nd.next]);
      }
      for (int64_t k = 0; k < n_touch; ++k) c.touch(touch_rows[k]);
      std::swap(h_cur, h_next);
    }
    sh.overflow = sh.n_unique > c.capacity;
    return;
  }
  const int64_t PF = 16;  // same DRAM-latency pipelining as the legacy walk
  for (int64_t t = p0; t < p1; ++t) {
    if (t + PF < p1) {
      const uint64_t sp = signs[pos[t + PF]];
      const uint64_t sh_home = c.scratch_mask & splitmix64(sp);
      __builtin_prefetch(&c.scratch[sh_home]);
      __builtin_prefetch(&c.table[c.home(sp)]);
      if (observing) __builtin_prefetch(&sh.obs_count[sh_home]);
    }
    const int64_t i = pos[t];
    const uint64_t s = signs[i];
    uint64_t j = c.scratch_mask & splitmix64(s);
    int64_t v;
    for (;;) {
      const Cache::ScratchSlot& sl = c.scratch[j];
      if ((sl.packed >> 32) != ep) { v = -1; break; }
      if (sl.sign == s) { v = (int32_t)(uint32_t)sl.packed; break; }
      j = (j + 1) & c.scratch_mask;
    }
    if (v == -1) {  // first time this batch
      ++sh.n_unique;
      const int64_t lpos = c.find_pos(s);
      if (lpos >= 0) {
        const int64_t r = c.table[lpos].row;
        c.touch(r);
        v = sh.row_base + r;
      } else if (!c.touch_admits(s)) {
        v = total_capacity;  // global pad row: zero fwd, grad dropped
      } else {
        v = -((int64_t)sh.miss_signs.size() + 2);
        sh.miss_signs.push_back(s);
      }
      c.scratch[j] = Cache::ScratchSlot{s, (ep << 32) | (uint32_t)(int32_t)v};
      if (observing) {
        const int64_t slot =
            slot_base + (samples_per_slot > 0 ? i / samples_per_slot : 0);
        sh.obs_count[j] = 1;
        sh.obs_slot[j] = slot < 0 ? UINT32_MAX : (uint32_t)slot;
        sh.obs_order.push_back((uint32_t)j);
      }
    } else if (observing) {
      ++sh.obs_count[j];  // repeat: slot attribution rides the first touch
    }
    rows_out[i] = (int32_t)v;
  }
  sh.overflow = sh.n_unique > c.capacity;
}

// phase B admit for one shard (caller holds sh.mu): assign rows to misses
// (evicting shard-LRU residents not in this batch), then resolve the
// placeholder LUT entries. Row values are global (row_base offset).
void shard_pass2(FeedShard& sh, int32_t* rows_out, const int64_t* pos,
                 int64_t p0, int64_t p1) {
  Cache& c = sh.dir;
  const int64_t n_miss = (int64_t)sh.miss_signs.size();
  sh.miss_rows.clear();
  sh.ev_signs.clear();
  sh.ev_rows.clear();
  // Probe-layout mode extends the wave discipline into the admit loop —
  // every miss sign is known upfront, so its insert-probe home lines ride
  // a rolling prefetch window, and each eviction prefetches the NEXT
  // LRU-tail node + its row sign one insert ahead of use. Pure prefetch:
  // the admit/evict sequence (the golden scalar reference) is unchanged.
  const int64_t PF2 = 8;
  if (c.probe_mode) {
    for (int64_t m = 0; m < std::min(PF2, n_miss); ++m) {
      const uint64_t hp = splitmix64(sh.miss_signs[m]);
      __builtin_prefetch(&c.tags[hp & c.mask]);
      __builtin_prefetch(&c.table[hp & c.mask]);
    }
    if (n_miss && c.count >= c.capacity && c.lru_tail >= 0) {
      __builtin_prefetch(&c.lru[c.lru_tail]);
      __builtin_prefetch(&c.row_sign[c.lru_tail]);
    }
  }
  for (int64_t m = 0; m < n_miss; ++m) {
    if (c.probe_mode && m + PF2 < n_miss) {
      const uint64_t hp = splitmix64(sh.miss_signs[m + PF2]);
      __builtin_prefetch(&c.tags[hp & c.mask]);
      __builtin_prefetch(&c.table[hp & c.mask]);
    }
    if (c.count >= c.capacity) {
      uint64_t ev_sign;
      const int64_t ev_row = c.evict_lru(&ev_sign);
      sh.ev_signs.push_back(ev_sign);
      sh.ev_rows.push_back(sh.row_base + ev_row);
      c.free_rows.push_back(ev_row);
      if (c.probe_mode && c.lru_tail >= 0) {
        __builtin_prefetch(&c.lru[c.lru_tail]);
        __builtin_prefetch(&c.row_sign[c.lru_tail]);
      }
    }
    sh.miss_rows.push_back(sh.row_base + c.insert(sh.miss_signs[m]));
  }
  for (int64_t t = p0; t < p1; ++t) {
    const int64_t i = pos[t];
    const int32_t v = rows_out[i];
    if (v < 0) rows_out[i] = (int32_t)sh.miss_rows[-(int64_t)v - 2];
  }
}

// fused observe apply for one shard: its private (slot, sign) occurrence
// scratch lands in the shard's private sub-sketch, first-seen order, one
// weighted observe per distinct pair. Caller must NOT hold sh.mu (leaf
// locks only). Final cm/totals/bitmap state is identical to per-position
// observes; the top-K list sees each pair once at its full batch weight.
void shard_observe_apply(FeedShard& sh, AccessSketch* sk) {
  if (sk == nullptr) return;
  const int64_t n_c = (int64_t)sh.obs_sign_c.size();
  if (n_c == 0 && sh.obs_order.empty()) return;
  if (n_c > 0) {
    // compact stream from the probe-wave walk: (sign, slot, count) in
    // first-seen order, read LINEARLY — the count-min lines are the only
    // non-streaming accesses left, and their addresses come straight off
    // the sequential sign read, so one short pipeline covers them. Same
    // triples in the same order as the scratch-indexed path below: the
    // sketch state stays bit-identical across probe modes.
    std::lock_guard<std::mutex> lk(sk->mu);
    const uint64_t k = (uint64_t)sk->sample_k;
    const int64_t PF = 8;
    for (int64_t t = 0; t < n_c; ++t) {
      if (t + PF < n_c) {
        const uint64_t keyp =
            sh.obs_sign_c[(size_t)(t + PF)] ^
            ((uint64_t)sh.obs_slot_c[(size_t)(t + PF)] * SK_SLOT_MIX);
        for (int64_t d = 0; d < sk->depth; ++d)
          __builtin_prefetch(
              &sk->cm[(size_t)(d * sk->width +
                               (int64_t)(splitmix64(keyp ^ SK_DEPTH_SEED[d]) &
                                         sk->width_mask))],
              1);
      }
      const int64_t slot = (int64_t)sh.obs_slot_c[(size_t)t];
      if (slot >= sk->n_slots) continue;  // incl. the UINT32_MAX sentinel
      const uint64_t sign = sh.obs_sign_c[(size_t)t];
      if (k > 1 && splitmix64(sign ^ SK_SAMPLE_SEED) % k != 0) continue;
      const uint32_t est =
          sk->observe_w(slot, sign, (uint64_t)sh.obs_cnt_c[(size_t)t] * k);
      sk->maybe_top(slot, sign, est);
    }
    return;
  }
  std::lock_guard<std::mutex> lk(sk->mu);
  const Cache& c = sh.dir;
  const uint64_t k = (uint64_t)sk->sample_k;
  const int64_t n = (int64_t)sh.obs_order.size();
  // Two-stage prefetch pipeline, same discipline as the admit walk: the
  // scratch entry is pulled at distance 2*PF, its count-min lines (whose
  // addresses need the sign from that entry) at distance PF. A sentinel
  // obs_slot just hashes to a garbage-but-masked in-bounds cm index.
  const int64_t PF = 8;
  for (int64_t t = 0; t < n; ++t) {
    if (t + 2 * PF < n)
      __builtin_prefetch(&c.scratch[sh.obs_order[(size_t)(t + 2 * PF)]]);
    if (t + PF < n) {
      const uint32_t jp = sh.obs_order[(size_t)(t + PF)];
      const uint64_t keyp =
          c.scratch[jp].sign ^ ((uint64_t)sh.obs_slot[jp] * SK_SLOT_MIX);
      for (int64_t d = 0; d < sk->depth; ++d)
        __builtin_prefetch(
            &sk->cm[(size_t)(d * sk->width +
                             (int64_t)(splitmix64(keyp ^ SK_DEPTH_SEED[d]) &
                                       sk->width_mask))],
            1);
    }
    const uint32_t j = sh.obs_order[(size_t)t];
    const int64_t slot = (int64_t)sh.obs_slot[j];
    if (slot >= sk->n_slots) continue;  // incl. the UINT32_MAX sentinel
    const uint64_t sign = c.scratch[j].sign;
    if (k > 1 && splitmix64(sign ^ SK_SAMPLE_SEED) % k != 0) continue;
    const uint32_t est =
        sk->observe_w(slot, sign, (uint64_t)sh.obs_count[j] * k);
    sk->maybe_top(slot, sign, est);
  }
}

// hazard-ledger probe of one shard's misses (same revalidation contract as
// cache_feed_batch: the caller re-checks hits after reserving the ring
// span). Caller must NOT hold sh.mu.
void shard_ledger_probe(FeedShard& sh, PendingMap* m, uint64_t salt) {
  sh.rst_src.clear();
  sh.rst_pos.clear();
  if (m == nullptr) return;
  std::lock_guard<std::mutex> lk(m->mu);
  if (m->count == 0) return;
  const int64_t n_miss = (int64_t)sh.miss_signs.size();
  const int64_t PF = 16;
  for (int64_t j = 0; j < n_miss; ++j) {
    if (j + PF < n_miss)
      __builtin_prefetch(
          &m->t[splitmix64(sh.miss_signs[j + PF] ^ salt) & m->mask]);
    int64_t src;
    uint32_t token;
    if (m->find(sh.miss_signs[j] ^ salt, &src, &token)) {
      sh.rst_src.push_back(src);
      sh.rst_pos.push_back(j);
    }
  }
}

}  // namespace

extern "C" {

// capacity split evenly across shards (first capacity % S shards get one
// extra row); n_shards clamped to [1, min(64, capacity)]; threads clamped
// to [1, n_shards]. part_salt is the PR 3 per-group salt — the partition
// key that keeps routing consistent with the pending-ledger namespace.
void* cache_create_sharded(int64_t capacity, int64_t n_shards,
                           uint64_t part_salt, int64_t threads) {
  if (capacity < 1) return nullptr;
  if (n_shards < 1) n_shards = 1;
  if (n_shards > SHARD_MAX) n_shards = SHARD_MAX;
  if (n_shards > capacity) n_shards = capacity;
  return new (std::nothrow) ShardedCache(capacity, n_shards, part_salt,
                                         threads);
}

void cache_sharded_destroy(void* h) { delete static_cast<ShardedCache*>(h); }

int64_t cache_sharded_len(void* h) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  int64_t total = 0;
  for (auto& sh : sc.shards) {  // one shard mu at a time, never nested
    std::lock_guard<std::mutex> lk(sh->mu);
    total += sh->dir.count;
  }
  return total;
}

int64_t cache_sharded_capacity(void* h) {
  return static_cast<ShardedCache*>(h)->total_capacity;
}

int64_t cache_sharded_n_shards(void* h) {
  return static_cast<ShardedCache*>(h)->n_shards;
}

int64_t cache_sharded_threads(void* h) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  std::lock_guard<std::mutex> lk(sc.pool_mu);
  return sc.n_threads;
}

void cache_sharded_set_threads(void* h, int64_t t) {
  static_cast<ShardedCache*>(h)->set_threads(t);
}

void cache_sharded_set_admit_touches(void* h, int64_t t) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  for (auto& sh : sc.shards) {
    std::lock_guard<std::mutex> lk(sh->mu);
    Cache& c = sh->dir;
    c.admit_touches = t < 1 ? 1 : (t > 255 ? 255 : t);
    if (c.admit_touches > 1) c.ensure_touch_table();
  }
}

// per-shard resident counts (out sized n_shards) — the stats surface
void cache_sharded_shard_sizes(void* h, int64_t* out) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  for (int64_t s = 0; s < sc.n_shards; ++s) {
    std::lock_guard<std::mutex> lk(sc.shards[s]->mu);
    out[s] = sc.shards[s]->dir.count;
  }
}

// per-shard walk time of the LAST feed in ns (out sized n_shards) — the
// profile_feeder per-shard table and the feeder_shard_busy gauges
void cache_sharded_shard_busy_ns(void* h, int64_t* out) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  for (int64_t s = 0; s < sc.n_shards; ++s)
    out[s] = sc.shards[s]->busy_ns.load(std::memory_order_relaxed);
}

// per-shard pool-queue wait of the LAST feed in ns (out sized n_shards):
// dispatch-to-walk-start summed over both phases. busy/stall together
// separate shard imbalance from core starvation on the gauge surface.
void cache_sharded_shard_stall_ns(void* h, int64_t* out) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  for (int64_t s = 0; s < sc.n_shards; ++s)
    out[s] = sc.shards[s]->stall_ns.load(std::memory_order_relaxed);
}

// probe layout selector for every shard directory: 1 = SIMD tag probe
// (default, PERSIA_FEED_PROBE), 0 = scalar slot walk. Taken under each
// shard's mu so a concurrent probe/feed never sees the mode flip
// mid-walk; output is bit-identical either way — this knob exists for
// the golden parity suite and A/B profiling.
void cache_sharded_set_probe_mode(void* h, int64_t mode) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  for (auto& sh : sc.shards) {
    std::lock_guard<std::mutex> lk(sh->mu);
    sh->dir.probe_mode = mode ? 1 : 0;
  }
}

int64_t cache_sharded_probe_mode(void* h) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  std::lock_guard<std::mutex> lk(sc.shards[0]->mu);
  return sc.shards[0]->dir.probe_mode;
}

// walker pinning policy (PERSIA_FEED_AFFINITY): 0 none, 1 compact,
// 2 spread. Respawns pool workers so the pin applies from thread start.
void cache_sharded_set_affinity(void* h, int64_t mode) {
  static_cast<ShardedCache*>(h)->set_affinity(mode);
}

int64_t cache_sharded_affinity(void* h) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  std::lock_guard<std::mutex> lk(sc.pool_mu);
  return sc.affinity_mode;
}

// read-only probe (no admit, no LRU touch): rows_out[i] = global row or -1.
// One pass per shard so a probe never takes more than one lock at a time
// and shares no scratch with a concurrent feed.
void cache_sharded_probe(void* h, const uint64_t* signs, int64_t n,
                         int64_t* rows_out) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  const int64_t S = sc.n_shards;
  for (int64_t s = 0; s < S; ++s) {
    FeedShard& sh = *sc.shards[s];
    std::lock_guard<std::mutex> lk(sh.mu);
    for (int64_t i = 0; i < n; ++i) {
      if (S != 1 && shard_route(signs[i], sc.part_salt, S) != s) continue;
      const int64_t pos = sh.dir.find_pos(signs[i]);
      rows_out[i] = pos >= 0 ? sh.row_base + sh.dir.table[pos].row : -1;
    }
  }
}

// deduped-batch admit (the general path's surface): same contract as
// cache_admit with global rows; miss_idx_out lists missing input indices
// in shard-merged order (ascending shard, input order within a shard).
// Returns -1 before mutating anything if any shard's routed distinct
// count exceeds its capacity.
int64_t cache_sharded_admit(void* h, const uint64_t* signs, int64_t n,
                            int64_t* rows_out, int64_t* miss_idx_out,
                            uint64_t* evict_signs_out, int64_t* evict_rows_out,
                            int64_t* n_evict_out) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  *n_evict_out = 0;
  const int64_t S = sc.n_shards;
  std::vector<int64_t> routed(S, 0);
  std::vector<uint8_t> sid(n);
  for (int64_t i = 0; i < n; ++i) {
    const int64_t s = S == 1 ? 0 : shard_route(signs[i], sc.part_salt, S);
    sid[i] = (uint8_t)s;
    ++routed[s];
  }
  for (int64_t s = 0; s < S; ++s)
    if (routed[s] > sc.shards[s]->dir.capacity) return -1;
  int64_t n_miss = 0, n_evict = 0;
  std::vector<int64_t> local_miss;
  for (int64_t s = 0; s < S; ++s) {
    FeedShard& sh = *sc.shards[s];
    std::lock_guard<std::mutex> lk(sh.mu);
    Cache& c = sh.dir;
    local_miss.clear();
    for (int64_t i = 0; i < n; ++i) {
      if (sid[i] != (uint8_t)s) continue;
      const int64_t pos = c.find_pos(signs[i]);
      if (pos >= 0) {
        const int64_t r = c.table[pos].row;
        c.touch(r);
        rows_out[i] = sh.row_base + r;
      } else if (!c.touch_admits(signs[i])) {
        rows_out[i] = sc.total_capacity;  // global pad row
      } else {
        local_miss.push_back(i);
      }
    }
    for (const int64_t i : local_miss) {
      if (c.count >= c.capacity) {
        uint64_t ev_sign;
        const int64_t ev_row = c.evict_lru(&ev_sign);
        evict_signs_out[n_evict] = ev_sign;
        evict_rows_out[n_evict] = sh.row_base + ev_row;
        ++n_evict;
        c.free_rows.push_back(ev_row);
      }
      rows_out[i] = sh.row_base + c.insert(signs[i]);
      miss_idx_out[n_miss++] = i;
    }
  }
  *n_evict_out = n_evict;
  return n_miss;
}

// resident (sign, global row) pairs, ascending shard order, MRU first
// within a shard. Non-destructive.
int64_t cache_sharded_snapshot(void* h, uint64_t* signs_out,
                               int64_t* rows_out) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  int64_t k = 0;
  for (auto& shp : sc.shards) {
    FeedShard& sh = *shp;
    std::lock_guard<std::mutex> lk(sh.mu);
    const Cache& c = sh.dir;
    for (int64_t r = c.lru_head; r >= 0; r = c.lru[r].next) {
      signs_out[k] = c.row_sign[r];
      rows_out[k] = sh.row_base + r;
      ++k;
    }
  }
  return k;
}

// drain every resident entry (flush-all at fences), same order as
// snapshot, and empty every shard.
int64_t cache_sharded_drain(void* h, uint64_t* signs_out, int64_t* rows_out) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  int64_t k = 0;
  for (auto& shp : sc.shards) {
    FeedShard& sh = *shp;
    std::lock_guard<std::mutex> lk(sh.mu);
    Cache& c = sh.dir;
    for (int64_t r = c.lru_head; r >= 0; r = c.lru[r].next) {
      signs_out[k] = c.row_sign[r];
      rows_out[k] = sh.row_base + r;
      ++k;
    }
    c.reset_directory();
  }
  return k;
}

// The sharded, single-pass feeder entry point. Same outputs and contract
// as cache_feed_batch (global rows; -1 on any shard's capacity overflow
// with nothing admitted), plus the fused observe: when `sketches` carries
// exactly n_shards AccessSketch handles, each shard's walk also lands its
// batch (slot, sign) occurrences in its private sub-sketch (position i
// belongs to slot_base + i / samples_per_slot, the flattened (S, B) group
// matrix convention; samples_per_slot <= 0 sends everything to slot_base).
// The fused observe attributes a sign to the slot of its FIRST position in
// the batch — exact whenever sign -> slot is injective (slot-prefixed
// signs, feature_index_prefix_bit > 0); the caller must keep the unfused
// observe path when that invariant does not hold. Pass sketches = NULL
// (or n_sketches != n_shards) to feed without observing. One feed per handle at a time — the caller serializes, as
// with the legacy entry point; probes/stats may run concurrently.
int64_t cache_feed_batch_sharded(
    void* h, void* pending_h, const uint64_t* signs, int64_t n,
    int32_t* rows_out, uint64_t* miss_signs_out, int64_t* miss_rows_out,
    uint64_t* evict_signs_out, int64_t* evict_rows_out,
    int64_t* n_unique_out, int64_t* n_evict_out, int64_t* restore_src_out,
    int64_t* restore_pos_out, int64_t* n_restore_out, uint64_t salt,
    void** sketches, int64_t n_sketches, int64_t samples_per_slot,
    int64_t slot_base) {
  ShardedCache& sc = *static_cast<ShardedCache*>(h);
  *n_unique_out = *n_evict_out = *n_restore_out = 0;
  const int64_t S = sc.n_shards;
  const bool observing = sketches != nullptr && n_sketches == S;
  // stable counting-sort bucketing: each shard's slice preserves input
  // order, so the per-shard walk is a pure function of (signs, shard
  // state) — independent of which thread runs it
  sc.sid.resize((size_t)n);
  sc.start.assign((size_t)S + 1, 0);
  sc.fill.assign((size_t)S, 0);
  sc.pos.resize((size_t)n);
  for (int64_t i = 0; i < n; ++i) {
    const int64_t s = S == 1 ? 0 : shard_route(signs[i], sc.part_salt, S);
    sc.sid[i] = (uint8_t)s;
    ++sc.start[s + 1];
  }
  for (int64_t s = 0; s < S; ++s) sc.start[s + 1] += sc.start[s];
  for (int64_t i = 0; i < n; ++i) {
    const int64_t s = sc.sid[i];
    sc.pos[sc.start[s] + sc.fill[s]++] = i;
  }
  // phase A: dedup/touch walks (+ fused occurrence scratch). Barriered
  // before phase B so an overflow anywhere bails before ANY shard admits.
  // t_dispatch anchors the per-shard stall counter: walk-start minus
  // dispatch is time the shard item sat in the pool queue (or behind
  // earlier items on the same worker) — queueing, not walking.
  const auto t_dispatch_a = std::chrono::steady_clock::now();
  sc.run_shards([&](int64_t s) {
    FeedShard& sh = *sc.shards[s];
    const auto t0 = std::chrono::steady_clock::now();
    sh.stall_ns.store(std::chrono::duration_cast<std::chrono::nanoseconds>(
                          t0 - t_dispatch_a)
                          .count(),
                      std::memory_order_relaxed);
    {
      std::lock_guard<std::mutex> lk(sh.mu);
      shard_pass1(sh, signs, rows_out, sc.pos.data(), sc.start[s],
                  sc.start[s + 1], sc.total_capacity, observing,
                  samples_per_slot, slot_base);
    }
    sh.busy_ns.store(std::chrono::duration_cast<std::chrono::nanoseconds>(
                         std::chrono::steady_clock::now() - t0)
                         .count(),
                     std::memory_order_relaxed);
  });
  for (int64_t s = 0; s < S; ++s)
    if (sc.shards[s]->overflow) return -1;
  // phase B: admit + placeholder resolution under the shard mu, then the
  // observe apply and ledger probe under their own (leaf) locks
  const auto t_dispatch_b = std::chrono::steady_clock::now();
  sc.run_shards([&](int64_t s) {
    FeedShard& sh = *sc.shards[s];
    const auto t0 = std::chrono::steady_clock::now();
    sh.stall_ns.fetch_add(std::chrono::duration_cast<std::chrono::nanoseconds>(
                              t0 - t_dispatch_b)
                              .count(),
                          std::memory_order_relaxed);
    {
      std::lock_guard<std::mutex> lk(sh.mu);
      shard_pass2(sh, rows_out, sc.pos.data(), sc.start[s], sc.start[s + 1]);
    }
    shard_observe_apply(
        sh, observing ? static_cast<AccessSketch*>(sketches[s]) : nullptr);
    shard_ledger_probe(sh, static_cast<PendingMap*>(pending_h), salt);
    sh.busy_ns.fetch_add(std::chrono::duration_cast<std::chrono::nanoseconds>(
                             std::chrono::steady_clock::now() - t0)
                             .count(),
                         std::memory_order_relaxed);
  });
  // deterministic shard-order merge
  int64_t n_miss = 0, n_unique = 0, n_evict = 0, n_restore = 0;
  for (int64_t s = 0; s < S; ++s) {
    FeedShard& sh = *sc.shards[s];
    const int64_t miss_base = n_miss;
    std::copy(sh.miss_signs.begin(), sh.miss_signs.end(),
              miss_signs_out + n_miss);
    std::copy(sh.miss_rows.begin(), sh.miss_rows.end(),
              miss_rows_out + n_miss);
    n_miss += (int64_t)sh.miss_signs.size();
    std::copy(sh.ev_signs.begin(), sh.ev_signs.end(),
              evict_signs_out + n_evict);
    std::copy(sh.ev_rows.begin(), sh.ev_rows.end(), evict_rows_out + n_evict);
    n_evict += (int64_t)sh.ev_signs.size();
    for (size_t j = 0; j < sh.rst_pos.size(); ++j) {
      restore_src_out[n_restore] = sh.rst_src[j];
      restore_pos_out[n_restore] = miss_base + sh.rst_pos[j];
      ++n_restore;
    }
    n_unique += sh.n_unique;
  }
  *n_unique_out = n_unique;
  *n_evict_out = n_evict;
  *n_restore_out = n_restore;
  return n_miss;
}

// the per-slot top-K heavy-hitter list (signs + decayed cm estimates, out
// arrays sized topk; unfilled entries are zero) — the Python side merges
// per-shard sub-sketch lists deterministically. Returns topk or -1.
int64_t sketch_slot_tops(void* h, int64_t slot, uint64_t* signs_out,
                         double* ests_out) {
  AccessSketch& sk = *static_cast<AccessSketch*>(h);
  std::lock_guard<std::mutex> lk(sk.mu);
  if (slot < 0 || slot >= sk.n_slots) return -1;
  for (int64_t k = 0; k < sk.topk; ++k) {
    signs_out[k] = sk.top_sign[(size_t)(slot * sk.topk + k)];
    ests_out[k] = sk.top_est[(size_t)(slot * sk.topk + k)];
  }
  return sk.topk;
}

// routed observe over a sub-sketch family: sign i lands in
// handles[shard_route(sign, part_salt, n_handles)], same partition as the
// sharded feeder, so the UNFUSED paths (ServiceCtx per-slot observes, PS
// slots) keep sub-sketch states consistent with the fused walk. One pass
// per handle (one lock at a time). Returns signs observed (incl. ones
// sampled away by each sketch's sample_k).
int64_t sketch_observe_routed(void** handles, int64_t n_handles,
                              uint64_t part_salt, const uint64_t* signs,
                              int64_t n, int64_t samples_per_slot,
                              int64_t slot_base) {
  if (handles == nullptr || n_handles < 1) return 0;
  if (n_handles == 1)
    return sketch_observe(handles[0], signs, n, samples_per_slot, slot_base);
  int64_t seen = 0;
  for (int64_t hs = 0; hs < n_handles; ++hs) {
    AccessSketch& sk = *static_cast<AccessSketch*>(handles[hs]);
    std::lock_guard<std::mutex> lk(sk.mu);
    const uint64_t k = (uint64_t)sk.sample_k;
    for (int64_t i = 0; i < n; ++i) {
      if (shard_route(signs[i], part_salt, n_handles) != hs) continue;
      const int64_t slot =
          slot_base + (samples_per_slot > 0 ? i / samples_per_slot : 0);
      if (slot < 0 || slot >= sk.n_slots) continue;
      if (k > 1 && splitmix64(signs[i] ^ SK_SAMPLE_SEED) % k != 0) {
        ++seen;
        continue;
      }
      const uint32_t est = sk.observe_w(slot, signs[i], k);
      sk.maybe_top(slot, signs[i], est);
      ++seen;
    }
  }
  return seen;
}

}  // extern "C"

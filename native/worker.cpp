// persia_tpu native embedding-worker hot loops.
//
// Capability parity with the reference's Rust embedding-worker tier
// (rust/persia-embedding-server/src/embedding_worker_service/mod.rs):
//   - per-slot id dedup feeding distinct-sign lookups
//     (FeatureBatch::new, persia-common/src/lib.rs:30-83)
//   - sum-pooling postprocess (lookup_batched_all_slots postprocess,
//     mod.rs:486-629, persia-simd add_assign_avx2)
//   - per-sign gradient accumulation on the update path
//     (update_all_batched_gradients, mod.rs:703-872)
//   - raw-slot index matrix construction (mod.rs:586-624)
//   - splitmix64 shard routing (sign_to_shard_modulo, mod.rs:342-345)
//
// Numeric contract with the numpy golden model
// (persia_tpu/embedding/worker.py): dedup returns distinct signs in
// first-seen order (np.unique returns sorted — both pair with a consistent
// inverse array, and all downstream math is order-independent);
// pooling/accumulation iterate elements in input order, so float sums are
// bit-identical to np.add.at. Parity is asserted in
// tests/test_native_worker.py.
//
// C ABI only (ctypes-friendly); no Python headers needed.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

inline uint64_t next_pow2(uint64_t v) {
  uint64_t c = 16;
  while (c < v) c <<= 1;
  return c;
}

}  // namespace

extern "C" {

// Dedup a flat array of u64 signs. Writes the distinct signs in FIRST-SEEN
// (insertion) order to `distinct_out` (capacity >= n) and each element's
// position in that array to `inverse_out` (size n). Returns the distinct
// count. Insertion order (vs np.unique's sorted order) is deterministic for
// a given input and 6x faster; the orderings are interchangeable because
// every consumer pairs `distinct` with `inverse` (pooling sums and gather
// results are order-independent).
int64_t wk_dedup(const uint64_t* ids, int64_t n, uint64_t* distinct_out,
                 int64_t* inverse_out) {
  if (n <= 0) return 0;
  const uint64_t cap = next_pow2(static_cast<uint64_t>(n) * 2);
  const uint64_t mask = cap - 1;
  struct Slot {
    uint64_t key;
    int32_t val;
  };
  std::vector<Slot> tab(cap, Slot{0, -1});
  int64_t m = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t s = ids[i];
    uint64_t h = splitmix64(s) & mask;
    for (;;) {
      if (tab[h].val < 0) {
        tab[h].key = s;
        tab[h].val = static_cast<int32_t>(m);
        distinct_out[m] = s;
        inverse_out[i] = m;
        ++m;
        break;
      }
      if (tab[h].key == s) {
        inverse_out[i] = tab[h].val;
        break;
      }
      h = (h + 1) & mask;
    }
  }
  return m;
}

// pooled[sample_of_id[i], :] += rows[inverse[i], :] for i in input order
// (bit-identical to np.add.at's sequential accumulation). `pooled` must be
// zero-initialized by the caller ((B, dim) f32).
void wk_sum_pool(const float* rows, const int64_t* inverse,
                 const int64_t* sample_of_id, int64_t n, int64_t dim,
                 float* pooled) {
  for (int64_t i = 0; i < n; ++i) {
    const float* src = rows + inverse[i] * dim;
    float* dst = pooled + sample_of_id[i] * dim;
    for (int64_t d = 0; d < dim; ++d) dst[d] += src[d];
  }
}

// per_distinct[inverse[i], :] += grad[sample_of_id[i], :] — the worker's
// per-sign gradient accumulation (mod.rs:703-872). `per_distinct` must be
// zero-initialized ((D, dim) f32).
void wk_grad_accum(const float* grad, const int64_t* inverse,
                   const int64_t* sample_of_id, int64_t n, int64_t dim,
                   float* per_distinct) {
  for (int64_t i = 0; i < n; ++i) {
    const float* src = grad + sample_of_id[i] * dim;
    float* dst = per_distinct + inverse[i] * dim;
    for (int64_t d = 0; d < dim; ++d) dst[d] += src[d];
  }
}

// Raw-slot index matrix: for each sample b, the first min(counts[b], L)
// positions hold that sample's entries of `inverse` (in order); the rest stay
// `pad`. `index_out` is (B, L) int32, NOT pre-filled by the caller.
void wk_raw_index(const int64_t* counts, const int64_t* inverse, int64_t B,
                  int64_t L, int32_t pad, int32_t* index_out) {
  int64_t pos = 0;
  for (int64_t b = 0; b < B; ++b) {
    int32_t* row = index_out + b * L;
    const int64_t take = counts[b] < L ? counts[b] : L;
    int64_t t = 0;
    for (; t < take; ++t) row[t] = static_cast<int32_t>(inverse[pos + t]);
    for (; t < L; ++t) row[t] = pad;
    pos += counts[b];
  }
}

// Fused shard partition: computes each sign's shard and writes, per shard,
// the member positions (into `pos_out`, grouped by shard with stable input
// order) and per-shard counts (`count_out`, size num_shards). Saves the
// num_shards boolean-mask passes the numpy router does.
// Single-id fast-path matrix build: out[s*B + b] = (ids[s][b] & mask) |
// prefix[s] — replaces the per-slot numpy prefix-OR + row copy loop that
// dominated the cached feeder's Python time (one call for all S slots).
// prefix_bit == 0 (or a zero prefix) degenerates to a plain copy.
void wk_build_sid_matrix(const uint64_t* const* ids, const uint64_t* prefixes,
                         int64_t S, int64_t B, int32_t prefix_bit,
                         uint64_t* out) {
  const uint64_t mask =
      prefix_bit > 0 ? ((~0ULL) >> prefix_bit) : ~0ULL;
  for (int64_t s = 0; s < S; ++s) {
    const uint64_t* src = ids[s];
    uint64_t* dst = out + s * B;
    const uint64_t p = prefixes[s];
    if (p == 0 || prefix_bit == 0) {
      std::memcpy(dst, src, sizeof(uint64_t) * B);
    } else {
      for (int64_t b = 0; b < B; ++b) dst[b] = (src[b] & mask) | p;
    }
  }
}

void wk_shard_partition(const uint64_t* signs, int64_t n, uint32_t num_shards,
                        int64_t* pos_out, int64_t* count_out) {
  std::vector<int64_t> shard(n);
  std::memset(count_out, 0, sizeof(int64_t) * num_shards);
  for (int64_t i = 0; i < n; ++i) {
    shard[i] = static_cast<int64_t>(splitmix64(signs[i]) % num_shards);
    ++count_out[shard[i]];
  }
  std::vector<int64_t> off(num_shards, 0);
  for (uint32_t s = 1; s < num_shards; ++s) off[s] = off[s - 1] + count_out[s - 1];
  for (int64_t i = 0; i < n; ++i) pos_out[off[shard[i]]++] = i;
}

}  // extern "C"

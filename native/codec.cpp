// persia_tpu native wire codec: an LZ4-block-format compressor/decompressor.
//
// Capability parity with the reference's RPC compression — lz4 FAST(3) on
// large frame bodies (`/root/reference/rust/others/persia-rpc/src/lib.rs:
// 68-145`). zlib (the round-1 fallback) is ~20x too slow to sit on the
// per-batch lookup/gradient path, so large frames effectively travelled
// uncompressed; this is the lz4-class replacement. The block FORMAT is the
// public LZ4 spec (token | literals | 2-byte LE offset | match-extension),
// so the bytes are interoperable with any standard lz4 block decoder; the
// implementation here is our own single-pass greedy matcher over a 4-byte
// hash window.
//
// C ABI only (ctypes-friendly).

#include <cstdint>
#include <cstring>

namespace {

constexpr int MINMATCH = 4;
// spec constraints: the last match must end >= 12 bytes before the block
// end and the last 5 bytes are always literals
constexpr int64_t MFLIMIT = 12;
constexpr int64_t LASTLITERALS = 5;

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash4(uint32_t v) {
  return (v * 2654435761u) >> 18;  // 14-bit table
}

constexpr uint32_t HASH_SIZE = 1u << 14;

}  // namespace

extern "C" {

int64_t lz4_compress_bound(int64_t n) { return n + n / 255 + 16; }

// Compress src[0..n) into dst (capacity cap). Returns compressed size, or
// -1 if dst is too small (use lz4_compress_bound).
int64_t lz4_compress(const uint8_t* src, int64_t n, uint8_t* dst, int64_t cap) {
  if (n < 0 || cap < lz4_compress_bound(n)) return -1;
  uint8_t* op = dst;
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  const uint8_t* anchor = ip;

  if (n >= MFLIMIT + MINMATCH) {
    const uint8_t* const mflimit = iend - MFLIMIT;
    int32_t table[HASH_SIZE];
    std::memset(table, -1, sizeof(table));

    while (ip < mflimit) {
      // find a 4-byte match via the hash table
      const uint32_t seq = read32(ip);
      const uint32_t h = hash4(seq);
      const int32_t cand = table[h];
      table[h] = (int32_t)(ip - src);
      if (cand < 0 || (ip - src) - cand > 0xFFFF ||
          read32(src + cand) != seq) {
        ++ip;
        continue;
      }
      const uint8_t* match = src + cand;
      // extend the match forward (stay clear of the tail literals zone)
      const uint8_t* const matchlimit = iend - LASTLITERALS;
      const uint8_t* mip = ip + MINMATCH;
      const uint8_t* mma = match + MINMATCH;
      while (mip < matchlimit && *mip == *mma) { ++mip; ++mma; }
      const int64_t mlen = mip - ip - MINMATCH;  // spec: stored as len-4
      const int64_t litlen = ip - anchor;

      // token
      uint8_t* token = op++;
      *token = 0;
      if (litlen >= 15) {
        *token = 15u << 4;
        int64_t rest = litlen - 15;
        while (rest >= 255) { *op++ = 255; rest -= 255; }
        *op++ = (uint8_t)rest;
      } else {
        *token = (uint8_t)(litlen << 4);
      }
      std::memcpy(op, anchor, (size_t)litlen);
      op += litlen;
      // offset
      const uint16_t off = (uint16_t)(ip - match);
      *op++ = (uint8_t)off;
      *op++ = (uint8_t)(off >> 8);
      // match length
      if (mlen >= 15) {
        *token |= 15;
        int64_t rest = mlen - 15;
        while (rest >= 255) { *op++ = 255; rest -= 255; }
        *op++ = (uint8_t)rest;
      } else {
        *token |= (uint8_t)mlen;
      }
      ip = mip;
      anchor = ip;
      // seed the table inside the match region sparsely (keeps the scan
      // O(n) while still catching repeats that start mid-match)
      if (ip < mflimit) table[hash4(read32(ip - 2))] = (int32_t)(ip - 2 - src);
    }
  }

  // trailing literals
  const int64_t litlen = iend - anchor;
  uint8_t* token = op++;
  if (litlen >= 15) {
    *token = 15u << 4;
    int64_t rest = litlen - 15;
    while (rest >= 255) { *op++ = 255; rest -= 255; }
    *op++ = (uint8_t)rest;
  } else {
    *token = (uint8_t)(litlen << 4);
  }
  std::memcpy(op, anchor, (size_t)litlen);
  op += litlen;
  return op - dst;
}

// Decompress src[0..n) into dst (exact capacity cap = original size).
// Returns decompressed size, or -1 on malformed/overflowing input.
int64_t lz4_decompress(const uint8_t* src, int64_t n, uint8_t* dst, int64_t cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  uint8_t* op = dst;
  uint8_t* const oend = dst + cap;

  while (ip < iend) {
    const uint8_t token = *ip++;
    // literals
    int64_t litlen = token >> 4;
    if (litlen == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        litlen += b;
      } while (b == 255);
    }
    if (ip + litlen > iend || op + litlen > oend) return -1;
    std::memcpy(op, ip, (size_t)litlen);
    ip += litlen;
    op += litlen;
    if (ip >= iend) break;  // last sequence has no match part
    // match
    if (ip + 2 > iend) return -1;
    const uint16_t off = (uint16_t)(ip[0] | (ip[1] << 8));
    ip += 2;
    if (off == 0 || op - dst < off) return -1;
    int64_t mlen = (token & 15) + MINMATCH;
    if ((token & 15) == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        mlen += b;
      } while (b == 255);
    }
    if (op + mlen > oend) return -1;
    const uint8_t* match = op - off;
    if (off >= mlen) {
      std::memcpy(op, match, (size_t)mlen);
      op += mlen;
    } else {
      // overlapping copy (run-length style) must go byte-wise
      while (mlen--) *op++ = *match++;
    }
  }
  return op - dst;
}

}  // extern "C"

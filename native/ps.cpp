// persia_tpu native parameter-server core.
//
// Capability parity with the reference's Rust embedding-parameter-server stack:
//   - sharded LRU embedding holder  (persia-embedding-holder/src/{sharded,eviction_map,
//     array_linked_list}.rs): here an open-addressing hash table per internal shard
//     with backward-shift deletion + an intrusive doubly-linked LRU over an entry slab.
//   - entry layout [emb | optimizer state] in one flat float vector with
//     seeded-by-sign deterministic init (emb_entry.rs:16-76).
//   - lookup/update semantics (embedding_parameter_service/mod.rs:162-262,359-427):
//     train lookup LRU-touches, admits misses behind a probability gate, re-inits on
//     dim mismatch; infer lookup returns zeros on miss; gradient update applies the
//     registered sparse optimizer then clamps to ±weight_bound.
//   - sparse optimizers SGD / Adagrad(+vectorwise shared) / Adam(+per-group beta
//     powers) (persia-common/src/optim.rs, persia-simd/src/lib.rs). The inner loops
//     are written to auto-vectorize under -O3 -mavx2 -mfma.
//
// Exact numeric contract with the Python golden model
// (persia_tpu/embedding/store.py): identical splitmix64 shard routing, admit gate,
// counter-mode uniform init, and per-element update formulas. Parity is asserted in
// tests/test_native_store.py.
//
// C ABI only (ctypes-friendly); no Python headers needed.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <map>
#include <mutex>
#include <new>
#include <unordered_map>
#include <vector>

namespace {

// ----------------------------------------------------------------- hashing

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// ---------------------------------------------------------------- optimizer

enum OptKind { OPT_NONE = -1, OPT_SGD = 0, OPT_ADAGRAD = 1, OPT_ADAM = 2 };

struct OptimizerConfig {
  int kind = OPT_NONE;
  float lr = 0.01f;
  float weight_decay = 0.f;
  float initialization = 0.01f;  // adagrad accumulator init
  float g_square_momentum = 1.f;
  float eps = 1e-10f;
  int vectorwise_shared = 0;
  float beta1 = 0.9f;
  float beta2 = 0.999f;

  uint32_t state_dim(uint32_t dim) const {
    switch (kind) {
      case OPT_SGD: return 0;
      case OPT_ADAGRAD: return vectorwise_shared ? 1 : dim;
      case OPT_ADAM: return 2 * dim;
      default: return 0;
    }
  }
};

// ------------------------------------------------------------------- shard

struct Entry {
  uint64_t sign;
  float* data;     // [emb | state], heap-owned
  uint32_t dim;    // embedding dim (first `dim` floats of data are the emb)
  uint32_t len;    // total floats = dim + optimizer state
  int32_t prev, next;  // LRU list links (entry slab indices)
};

struct Shard {
  // open-addressing table: table_sign/table_slot parallel arrays, pow2 size
  std::vector<uint64_t> table_sign;
  std::vector<int32_t> table_slot;  // -1 = empty, else index into entries
  std::vector<Entry> entries;
  std::vector<int32_t> free_list;
  int32_t lru_head = -1;  // most recently used
  int32_t lru_tail = -1;  // least recently used
  size_t count = 0;
  size_t max_entries = 0;
  size_t mask = 0;
  std::mutex mu;

  void init(size_t cap) {
    max_entries = cap ? cap : 1;
    size_t tsize = 4;
    while (tsize < max_entries * 2) tsize <<= 1;
    table_sign.assign(tsize, 0);
    table_slot.assign(tsize, -1);
    mask = tsize - 1;
    entries.reserve(max_entries);
  }

  inline size_t home(uint64_t sign) const { return splitmix64(sign) & mask; }

  // returns table position of sign or SIZE_MAX
  size_t find_pos(uint64_t sign) const {
    size_t i = home(sign);
    while (table_slot[i] >= 0) {
      if (table_sign[i] == sign) return i;
      i = (i + 1) & mask;
    }
    return SIZE_MAX;
  }

  void lru_unlink(int32_t e) {
    Entry& en = entries[e];
    if (en.prev >= 0) entries[en.prev].next = en.next; else lru_head = en.next;
    if (en.next >= 0) entries[en.next].prev = en.prev; else lru_tail = en.prev;
    en.prev = en.next = -1;
  }

  void lru_push_front(int32_t e) {
    Entry& en = entries[e];
    en.prev = -1;
    en.next = lru_head;
    if (lru_head >= 0) entries[lru_head].prev = e;
    lru_head = e;
    if (lru_tail < 0) lru_tail = e;
  }

  void touch(int32_t e) {
    if (lru_head == e) return;
    lru_unlink(e);
    lru_push_front(e);
  }

  // backward-shift deletion at table position pos (linear probing invariant kept)
  void erase_table_pos(size_t i) {
    size_t j = i;
    for (;;) {
      table_slot[i] = -1;
      size_t k;
      for (;;) {
        j = (j + 1) & mask;
        if (table_slot[j] < 0) return;
        k = home(table_sign[j]);
        // move j back to i unless j's home lies cyclically in (i, j]
        bool home_in_range = (i <= j) ? (i < k && k <= j) : (i < k || k <= j);
        if (!home_in_range) break;
      }
      table_sign[i] = table_sign[j];
      table_slot[i] = table_slot[j];
      i = j;
    }
  }

  void remove_entry(int32_t e) {
    size_t pos = find_pos(entries[e].sign);
    if (pos != SIZE_MAX) erase_table_pos(pos);
    lru_unlink(e);
    std::free(entries[e].data);
    entries[e].data = nullptr;
    free_list.push_back(e);
    --count;
  }

  void evict_lru() {
    if (lru_tail >= 0) remove_entry(lru_tail);
  }

  // insert new sign (must not exist); returns entry index with uninit data ptr
  int32_t insert(uint64_t sign, uint32_t dim, uint32_t len) {
    if (count >= max_entries) evict_lru();
    int32_t e;
    if (!free_list.empty()) {
      e = free_list.back();
      free_list.pop_back();
    } else {
      entries.push_back(Entry{});
      e = (int32_t)entries.size() - 1;
    }
    Entry& en = entries[e];
    en.sign = sign;
    en.dim = dim;
    en.len = len;
    en.data = (float*)std::malloc(sizeof(float) * len);
    en.prev = en.next = -1;
    size_t i = home(sign);
    while (table_slot[i] >= 0) i = (i + 1) & mask;
    table_sign[i] = sign;
    table_slot[i] = e;
    lru_push_front(e);
    ++count;
    return e;
  }

  ~Shard() {
    for (auto& en : entries)
      if (en.data) std::free(en.data);
  }
};

// ------------------------------------------------------------------- store

struct Store {
  std::vector<Shard> shards;
  uint32_t num_shards;
  uint64_t seed;
  // hyperparameters (configure())
  double init_lo = -0.01, init_hi = 0.01;
  // init distribution (ps_set_init_method): 0=uniform 1=gamma 2=poisson
  // 3=normal 4=inverse_sqrt; p0/p1 per-kind params (config.py INIT_KIND_CODES)
  int init_kind = 0;
  double init_p0 = -0.01, init_p1 = 0.01;
  double admit_prob = 1.0;
  float weight_bound = 10.f;
  OptimizerConfig opt;
  std::map<int, std::pair<double, double>> batch_state;  // group -> (b1^t, b2^t)
  std::mutex batch_mu;

  // Bounded apply-journal (crash-consistent trainer resume): ids of
  // gradient batches already applied between snapshot fences, each with a
  // crc32 of its payload. A resuming trainer probes before re-applying a
  // replayed batch — present+matching means "already applied, skip"
  // (exactly-once), present+mismatching means the replay diverged (error).
  // FIFO-bounded: the ring evicts the oldest id once `journal_cap` is
  // reached, which is safe because a resume only replays ids newer than
  // the last committed fence.
  std::unordered_map<uint64_t, uint32_t> journal_map;  // id -> payload crc
  std::vector<uint64_t> journal_ring;                  // insertion order
  size_t journal_cap = 1 << 16;
  size_t journal_head = 0;  // ring slot the next insert overwrites when full
  std::mutex journal_mu;

  void journal_record(uint64_t id, uint32_t crc) {
    std::lock_guard<std::mutex> g(journal_mu);
    auto it = journal_map.find(id);
    if (it != journal_map.end()) {
      it->second = crc;
      return;
    }
    if (journal_ring.size() < journal_cap) {
      journal_ring.push_back(id);
    } else {
      journal_map.erase(journal_ring[journal_head]);
      journal_ring[journal_head] = id;
      journal_head = (journal_head + 1) % journal_cap;
    }
    journal_map.emplace(id, crc);
  }

  // 1 = applied (crc matches), 0 = unknown, -1 = applied w/ different crc
  int journal_probe(uint64_t id, uint32_t crc) {
    std::lock_guard<std::mutex> g(journal_mu);
    auto it = journal_map.find(id);
    if (it == journal_map.end()) return 0;
    return it->second == crc ? 1 : -1;
  }

  void journal_clear() {
    std::lock_guard<std::mutex> g(journal_mu);
    journal_map.clear();
    journal_ring.clear();
    journal_head = 0;
  }

  Store(uint64_t capacity, uint32_t n_shards, uint64_t seed_) : shards(n_shards) {
    num_shards = n_shards;
    seed = seed_;
    size_t per = capacity / n_shards;
    if (per < 1) per = 1;
    for (auto& s : shards) s.init(per);
  }

  inline Shard& shard_of(uint64_t sign) {
    // identical to the Python golden model: splitmix64(sign ^ 0xA5A5A5A5) % n
    return shards[splitmix64(sign ^ 0xA5A5A5A5ULL) % num_shards];
  }

  inline bool admit(uint64_t sign) const {
    if (admit_prob >= 1.0) return true;
    if (admit_prob <= 0.0) return false;
    uint64_t h = splitmix64(sign ^ 0xC0FFEEULL);
    return (double)(h % (1ULL << 24)) / (double)(1ULL << 24) < admit_prob;
  }

  // counter-mode uniform init, bit-identical to hashing.uniform_init_for_sign
  void uniform_row(uint64_t sign, uint32_t dim, double lo, double hi,
                   float* out) const {
    uint64_t base = splitmix64(sign ^ seed);
    double range = hi - lo;
    for (uint32_t i = 0; i < dim; ++i) {
      uint64_t s = splitmix64(base + i);
      double u = (double)(s >> 11) * kToUnit;
      out[i] = (float)(lo + u * range);
    }
  }

  // Seeded init distributions beyond uniform (ref: emb_entry.rs:28-60).
  // Per-element splitmix64 substreams + glibc libm transcendentals — the
  // EXACT algorithms of hashing.py _normal_from/_poisson_from/_gamma_from
  // (CPython math.* calls the same libm), so rows are bit-identical to the
  // Python golden model; pinned by tests/test_init_methods.py.
  static constexpr double kToUnit = 1.0 / 9007199254740992.0;  // 2^-53
  static constexpr double kTwoPi = 6.283185307179586;

  struct SubStream {
    uint64_t b;
    uint64_t j = 0;
    SubStream(uint64_t base, uint64_t i) : b(splitmix64(base + i)) {}
    double next() { return (double)(splitmix64(b + 1 + j++) >> 11) * kToUnit; }
  };

  static double normal_from(SubStream& st, double mean, double std_) {
    double u1 = st.next();
    if (u1 < kToUnit) u1 = kToUnit;
    double u2 = st.next();
    return mean + std_ * (std::sqrt(-2.0 * std::log(u1)) * std::cos(kTwoPi * u2));
  }

  static double poisson_from(SubStream& st, double lam) {
    if (lam <= 0.0) return 0.0;
    double big_l = std::exp(-lam);
    int k = 0;
    double p = 1.0;
    while (k < 4096) {  // hard cap mirrored in hashing.py
      ++k;
      p *= st.next();
      if (!(p > big_l)) break;
    }
    return (double)(k - 1);
  }

  static double gamma_from(SubStream& st, double shape, double scale) {
    if (shape <= 0.0) return 0.0;
    double boost = 1.0, k = shape;
    if (k < 1.0) {
      double u = st.next();
      if (u < kToUnit) u = kToUnit;
      boost = std::pow(u, 1.0 / k);
      k += 1.0;
    }
    double d = k - 1.0 / 3.0;
    double c = 1.0 / (3.0 * std::sqrt(d));
    for (int it = 0; it < 1024; ++it) {  // cap mirrored in hashing.py
      double x = normal_from(st, 0.0, 1.0);
      double v = 1.0 + c * x;
      if (v <= 0.0) continue;
      v = v * v * v;
      double u = st.next();
      if (u < 1.0 - 0.0331 * x * x * x * x) return boost * d * v * scale;
      double lu = std::log(u < kToUnit ? kToUnit : u);
      if (lu < 0.5 * x * x + d * (1.0 - v + std::log(v)))
        return boost * d * v * scale;
    }
    return boost * d * scale;  // pathological-params fallback (same in Python)
  }

  void init_embedding(uint64_t sign, uint32_t dim, float* out) const {
    switch (init_kind) {
      case 0:  // uniform
        return uniform_row(sign, dim, init_p0, init_p1, out);
      case 4: {  // inverse_sqrt: uniform in ±1/sqrt(dim)
        double b = 1.0 / std::sqrt((double)dim);
        return uniform_row(sign, dim, -b, b, out);
      }
    }
    uint64_t base = splitmix64(sign ^ seed);
    for (uint32_t i = 0; i < dim; ++i) {
      SubStream st(base, i);
      double v = 0.0;
      if (init_kind == 3) v = normal_from(st, init_p0, init_p1);
      else if (init_kind == 2) v = poisson_from(st, init_p0);
      else if (init_kind == 1) v = gamma_from(st, init_p0, init_p1);
      out[i] = (float)v;
    }
  }

  void init_state(uint32_t dim, float* state) const {
    uint32_t sd = opt.state_dim(dim);
    if (opt.kind == OPT_ADAGRAD) {
      for (uint32_t i = 0; i < sd; ++i) state[i] = opt.initialization;
    } else {
      std::memset(state, 0, sizeof(float) * sd);
    }
  }

  std::pair<double, double> get_batch_state(int group) {
    std::lock_guard<std::mutex> g(batch_mu);
    auto it = batch_state.find(group);
    if (it != batch_state.end()) return it->second;
    // default: one advance from (1,1) — matches the Python store
    return {(double)opt.beta1, (double)opt.beta2};
  }

  void advance_batch_state(int group) {
    if (opt.kind != OPT_ADAM) return;
    std::lock_guard<std::mutex> g(batch_mu);
    auto it = batch_state.find(group);
    if (it == batch_state.end()) {
      batch_state[group] = {(double)opt.beta1, (double)opt.beta2};
    } else {
      it->second.first *= opt.beta1;
      it->second.second *= opt.beta2;
    }
  }

  void update_entry(float* emb, float* state, const float* grad_in, uint32_t dim,
                    std::pair<double, double> bs) {
    switch (opt.kind) {
      case OPT_SGD: {
        const float lr = opt.lr, wd = opt.weight_decay;
        if (wd != 0.f) {
          for (uint32_t i = 0; i < dim; ++i) emb[i] -= lr * (grad_in[i] + wd * emb[i]);
        } else {
          for (uint32_t i = 0; i < dim; ++i) emb[i] -= lr * grad_in[i];
        }
        break;
      }
      case OPT_ADAGRAD: {
        const float lr = opt.lr, wd = opt.weight_decay, mom = opt.g_square_momentum,
                    eps = opt.eps;
        if (opt.vectorwise_shared) {
          // shared accumulator = mean(g^2); double accumulation like numpy
          double g2 = 0.0;
          for (uint32_t i = 0; i < dim; ++i) {
            float g = grad_in[i] + (wd != 0.f ? wd * emb[i] : 0.f);
            g2 += (double)g * (double)g;
          }
          g2 /= (double)dim;
          state[0] = state[0] * mom + (float)g2;
          float denom = std::sqrt(state[0] + eps);
          for (uint32_t i = 0; i < dim; ++i) {
            float g = grad_in[i] + (wd != 0.f ? wd * emb[i] : 0.f);
            emb[i] -= lr * g / denom;
          }
        } else {
          for (uint32_t i = 0; i < dim; ++i) {
            float g = grad_in[i] + (wd != 0.f ? wd * emb[i] : 0.f);
            state[i] = state[i] * mom + g * g;
            emb[i] -= lr * g / std::sqrt(state[i] + eps);
          }
        }
        break;
      }
      case OPT_ADAM: {
        const float lr = opt.lr, wd = opt.weight_decay, b1 = opt.beta1, b2 = opt.beta2,
                    eps = opt.eps;
        float* m = state;
        float* v = state + dim;
        const float bc1 = (float)(1.0 - bs.first);
        const float bc2 = (float)(1.0 - bs.second);
        for (uint32_t i = 0; i < dim; ++i) {
          float g = grad_in[i] + (wd != 0.f ? wd * emb[i] : 0.f);
          m[i] = b1 * m[i] + (1.f - b1) * g;
          v[i] = b2 * v[i] + (1.f - b2) * g * g;
          float m_hat = m[i] / bc1;
          float v_hat = v[i] / bc2;
          emb[i] -= lr * m_hat / (std::sqrt(v_hat) + eps);
        }
        break;
      }
      default:
        break;
    }
    if (weight_bound > 0.f) {
      const float b = weight_bound;
      for (uint32_t i = 0; i < dim; ++i) {
        if (emb[i] > b) emb[i] = b;
        else if (emb[i] < -b) emb[i] = -b;
      }
    }
  }
};

// What a row does once its entry (or miss) is resolved:
//   kDefer  — read/update the entry's data; the engine applies it in order
//             with the data lines prefetched (classify prefetches them)
//   kDone   — fully handled inside classify (zero-fill, warm=0, skip)
//   kMutate — needs structural mutation (insert/evict/re-init); the engine
//             drains earlier rows, runs `mutate` sequentially, then
//             re-resolves everything after it (an insert can change what a
//             later duplicate sign resolves to)
enum class RowAction : int8_t { kDefer = 0, kDone = 1, kMutate = 2 };

// Shard-grouped row walk shared by the batched lookup/update entry points:
// stable counting sort of row indices by owning shard, then one shard at a
// time — ONE lock per touched shard instead of per row. Within a shard,
// rows process in chunks through a 4-pass software pipeline:
//   1. prefetch the chunk's home buckets        (table spans 100s of MB)
//   2. probe (buckets hot) + prefetch Entry structs
//   3. classify (structs hot) + prefetch entry data rows
//   4. apply in original order (data hot)
// Each pass issues up to CHUNK independent DRAM loads concurrently instead
// of one dependent chain per row — the walk is memory-latency bound, and
// this is where the per-row cost goes from ~4 serialized misses to ~4
// misses amortized over the whole chunk. Passes 1-3 are read-only; applies
// and mutations run in the rows' ORIGINAL relative order, so the resulting
// table/LRU/optimizer state is IDENTICAL to the sequential per-row walk
// (shards are independent state; stability of the counting sort preserves
// within-shard order).
template <class Classify, class Apply, class Mutate>
inline void walk_rows_by_shard(Store* s, const uint64_t* signs, int64_t n,
                               Classify&& classify, Apply&& apply,
                               Mutate&& mutate) {
  const uint32_t ns = s->num_shards;
  thread_local std::vector<uint32_t> cnt;
  thread_local std::vector<uint32_t> shard_idx;
  thread_local std::vector<int64_t> order;
  cnt.assign(ns + 1, 0);
  if ((int64_t)shard_idx.size() < n) { shard_idx.resize(n); order.resize(n); }
  for (int64_t i = 0; i < n; ++i) {
    shard_idx[i] = (uint32_t)(splitmix64(signs[i] ^ 0xA5A5A5A5ULL) % ns);
    cnt[shard_idx[i] + 1]++;
  }
  for (uint32_t r = 0; r < ns; ++r) cnt[r + 1] += cnt[r];
  {
    thread_local std::vector<uint32_t> ofs;
    ofs.assign(cnt.begin(), cnt.end() - 1);
    for (int64_t i = 0; i < n; ++i) order[ofs[shard_idx[i]]++] = i;
  }
  constexpr int64_t CHUNK = 32;
  int32_t ent[CHUNK];
  RowAction act[CHUNK];
  for (uint32_t r = 0; r < ns; ++r) {
    int64_t k = cnt[r];
    const int64_t k_end = cnt[r + 1];
    if (k == k_end) continue;
    Shard& sh = s->shards[r];
    std::lock_guard<std::mutex> g(sh.mu);
    while (k < k_end) {
      const int64_t m = std::min(CHUNK, k_end - k);
      for (int64_t j = 0; j < m; ++j) {
        const size_t hp = sh.home(signs[order[k + j]]);
        __builtin_prefetch(&sh.table_sign[hp]);
        __builtin_prefetch(&sh.table_slot[hp]);
      }
      for (int64_t j = 0; j < m; ++j) {
        const size_t pos = sh.find_pos(signs[order[k + j]]);
        const int32_t e = (pos == SIZE_MAX) ? -1 : sh.table_slot[pos];
        ent[j] = e;
        if (e >= 0) __builtin_prefetch(&sh.entries[e]);
      }
      // classification stops at the first mutation: a structural change can
      // alter what every later row resolves to (duplicate-sign inserts)
      int64_t stop = m;
      for (int64_t j = 0; j < m; ++j) {
        act[j] = classify(sh, order[k + j], ent[j]);
        if (act[j] == RowAction::kMutate) { stop = j; break; }
      }
      for (int64_t j = 0; j < stop; ++j)
        if (act[j] == RowAction::kDefer) apply(sh, order[k + j], ent[j]);
      if (stop < m) {
        mutate(sh, order[k + stop]);
        k += stop + 1;
        // Drain a RUN of consecutive mutations sequentially (cold fill
        // classifies nearly every row kMutate; restarting the 32-row
        // pipeline to consume one row per pass would redo ~16x the probe
        // work). Back to chunked mode at the first non-mutating row.
        while (k < k_end) {
          const int64_t i = order[k];
          const size_t pos = sh.find_pos(signs[i]);
          const int32_t e = (pos == SIZE_MAX) ? -1 : sh.table_slot[pos];
          const RowAction a = classify(sh, i, e);
          if (a == RowAction::kMutate) {
            mutate(sh, i);
            ++k;
            continue;
          }
          if (a == RowAction::kDefer) apply(sh, i, e);
          ++k;
          break;
        }
      } else {
        k += m;
      }
    }
  }
}

// data-row prefetch helper for classify passes
inline void prefetch_row(const float* data, uint32_t n_floats) {
  for (uint32_t o = 0; o < n_floats; o += 16) __builtin_prefetch(data + o);
}

}  // namespace

// ------------------------------------------------------------------- C API

extern "C" {

void* ps_create(uint64_t capacity, uint32_t num_shards, uint64_t seed) {
  if (capacity == 0 || num_shards == 0) return nullptr;
  return new (std::nothrow) Store(capacity, num_shards, seed);
}

void ps_destroy(void* h) { delete (Store*)h; }

void ps_configure(void* h, double init_lo, double init_hi, double admit_prob,
                  float weight_bound) {
  Store* s = (Store*)h;
  s->init_lo = init_lo;
  s->init_hi = init_hi;
  // keep the uniform params in sync for callers that never push an explicit
  // init method (ps_set_init_method overrides these after)
  if (s->init_kind == 0) {
    s->init_p0 = init_lo;
    s->init_p1 = init_hi;
  }
  s->admit_prob = admit_prob;
  s->weight_bound = weight_bound;
}

void ps_set_init_method(void* h, int kind, double p0, double p1) {
  Store* s = (Store*)h;
  s->init_kind = kind;
  s->init_p0 = p0;
  s->init_p1 = p1;
}

void ps_register_optimizer(void* h, int kind, float lr, float weight_decay,
                           float initialization, float g_square_momentum, float eps,
                           int vectorwise_shared, float beta1, float beta2) {
  Store* s = (Store*)h;
  s->opt = OptimizerConfig{kind, lr, weight_decay, initialization, g_square_momentum,
                           eps, vectorwise_shared, beta1, beta2};
  std::lock_guard<std::mutex> g(s->batch_mu);
  s->batch_state.clear();
}

uint32_t ps_num_shards(void* h) { return ((Store*)h)->num_shards; }

// Multi-slot batched lookup: ONE call per training batch instead of one per
// slot (the per-slot fan-out was measurable pure overhead on a 1-core host;
// reference batches the same way — lookup_batched_all_slots,
// embedding_worker_service/mod.rs:874-942). Group g covers rows
// [key_ofs[g], key_ofs[g+1]) of `signs` with embedding dim dims[g]; its rows
// are written at out + out_ofs[g] (float offset), row-major. State effects
// (LRU order, admits, evictions) are identical to per-slot sequential calls
// — see walk_rows_by_shard.
void ps_lookup_batched(void* h, const uint64_t* signs, const int64_t* key_ofs,
                       const uint32_t* dims, const int64_t* out_ofs,
                       int32_t n_groups, int train, float* out) {
  Store* s = (Store*)h;
  const int64_t n = n_groups > 0 ? key_ofs[n_groups] : 0;
  if (n == 0) return;
  // per-row group resolution (rows are contiguous per group)
  thread_local std::vector<int32_t> row_group;
  if ((int64_t)row_group.size() < n) row_group.resize(n);
  for (int32_t g = 0; g < n_groups; ++g)
    for (int64_t i = key_ofs[g]; i < key_ofs[g + 1]; ++i) row_group[i] = g;
  thread_local std::vector<uint32_t> entry_lens;
  entry_lens.resize(n_groups);
  for (int32_t g = 0; g < n_groups; ++g)
    entry_lens[g] = dims[g] + s->opt.state_dim(dims[g]);

  auto row_ptr = [&](int64_t i) {
    const int32_t g = row_group[i];
    return out + out_ofs[g] + (size_t)(i - key_ofs[g]) * dims[g];
  };
  walk_rows_by_shard(
      s, signs, n,
      [&](Shard& sh, int64_t i, int32_t e) {
        const int32_t g = row_group[i];
        const uint32_t dim = dims[g];
        if (e >= 0 && sh.entries[e].dim == dim &&
            (!train || sh.entries[e].len == entry_lens[g])) {
          prefetch_row(sh.entries[e].data, dim);
          return RowAction::kDefer;
        }
        if (!train) {  // infer: zeros on miss/mismatch — never read state
          std::memset(row_ptr(i), 0, sizeof(float) * dim);
          return RowAction::kDone;
        }
        if (e < 0 && !s->admit(signs[i])) {
          std::memset(row_ptr(i), 0, sizeof(float) * dim);
          return RowAction::kDone;
        }
        return RowAction::kMutate;  // admit-miss insert or dim-mismatch re-init
      },
      [&](Shard& sh, int64_t i, int32_t e) {
        if (train) sh.touch(e);
        std::memcpy(row_ptr(i), sh.entries[e].data,
                    sizeof(float) * dims[row_group[i]]);
      },
      [&](Shard& sh, int64_t i) {
        const int32_t g = row_group[i];
        const uint32_t dim = dims[g];
        const uint64_t sign = signs[i];
        size_t pos = sh.find_pos(sign);
        int32_t e = (pos == SIZE_MAX) ? -1 : sh.table_slot[pos];
        if (e >= 0) sh.remove_entry(e);  // dim mismatch → re-init
        int32_t ne = sh.insert(sign, dim, entry_lens[g]);
        float* data = sh.entries[ne].data;
        s->init_embedding(sign, dim, data);
        s->init_state(dim, data + dim);
        std::memcpy(row_ptr(i), data, sizeof(float) * dim);
      });
}

// out: (n, dim) row-major f32
void ps_lookup(void* h, const uint64_t* signs, int64_t n, uint32_t dim, int train,
               float* out) {
  const int64_t key_ofs[2] = {0, n};
  const int64_t out_ofs[1] = {0};
  ps_lookup_batched(h, signs, key_ofs, &dim, out_ofs, 1, train, out);
}

// Batched full-entry checkout for the HBM cache tier
// (persia_tpu/embedding/hbm_cache.py): like a train lookup, but copies the
// whole [emb | optimizer state] row so the device-side sparse optimizer
// continues from the PS's accumulated state. Misses are admitted
// unconditionally (the cache tier owns admission; write-back re-inserts on
// eviction either way) with the same seeded init as ps_lookup. Entries with
// a mismatched dim are re-initialized, matching lookup. `out` is
// (n, dim + state_dim) row-major. Returns the entry length.
int64_t ps_checkout(void* h, const uint64_t* signs, int64_t n, uint32_t dim,
                    float* out) {
  Store* s = (Store*)h;
  const uint32_t entry_len = dim + s->opt.state_dim(dim);
  walk_rows_by_shard(
      s, signs, n,
      [&](Shard& sh, int64_t, int32_t e) {
        if (e >= 0 && sh.entries[e].dim == dim && sh.entries[e].len == entry_len) {
          prefetch_row(sh.entries[e].data, entry_len);
          return RowAction::kDefer;
        }
        return RowAction::kMutate;
      },
      [&](Shard& sh, int64_t i, int32_t e) {
        sh.touch(e);
        std::memcpy(out + (size_t)i * entry_len, sh.entries[e].data,
                    sizeof(float) * entry_len);
      },
      [&](Shard& sh, int64_t i) {
        const uint64_t sign = signs[i];
        size_t pos = sh.find_pos(sign);
        int32_t e = (pos == SIZE_MAX) ? -1 : sh.table_slot[pos];
        if (e >= 0) sh.remove_entry(e);  // dim mismatch → re-init
        int32_t ne = sh.insert(sign, dim, entry_len);
        float* data = sh.entries[ne].data;
        s->init_embedding(sign, dim, data);
        s->init_state(dim, data + dim);
        std::memcpy(out + (size_t)i * entry_len, data, sizeof(float) * entry_len);
      });
  return entry_len;
}

// Warm/cold split for the HBM cache tier: rows whose sign exists
// (dim-matched) copy their full [emb | state] entry into `out` with an LRU
// touch and set warm_out[i]=1; cold signs are NOT admitted (the cache owns
// them until its eviction write-back re-inserts) and leave out untouched.
// Returns the entry length.
int64_t ps_probe_entries(void* h, const uint64_t* signs, int64_t n, uint32_t dim,
                         float* out, uint8_t* warm_out) {
  Store* s = (Store*)h;
  const uint32_t entry_len = dim + s->opt.state_dim(dim);
  walk_rows_by_shard(
      s, signs, n,
      [&](Shard& sh, int64_t i, int32_t e) {
        if (e >= 0 && sh.entries[e].dim == dim && sh.entries[e].len == entry_len) {
          prefetch_row(sh.entries[e].data, entry_len);
          return RowAction::kDefer;
        }
        warm_out[i] = 0;
        return RowAction::kDone;
      },
      [&](Shard& sh, int64_t i, int32_t e) {
        sh.touch(e);
        std::memcpy(out + (size_t)i * entry_len, sh.entries[e].data,
                    sizeof(float) * entry_len);
        warm_out[i] = 1;
      },
      [](Shard&, int64_t) {});  // probe never mutates
  return entry_len;
}

void ps_advance_batch_state(void* h, int group) { ((Store*)h)->advance_batch_state(group); }

// Multi-slot batched gradient update: ONE call per gradient batch. Group g
// covers rows [key_ofs[g], key_ofs[g+1]) with dim dims[g], gradient rows at
// grads + grad_ofs[g], and optimizer group opt_groups[g] (Adam batch-level
// beta powers are fetched once per group — the caller advances them once per
// gradient batch, matching optim.rs:99-221). State-identical to per-slot
// sequential calls (walk_rows_by_shard preserves within-shard order).
int ps_update_batched(void* h, const uint64_t* signs, const int64_t* key_ofs,
                      const uint32_t* dims, const float* grads,
                      const int64_t* grad_ofs, const int32_t* opt_groups,
                      int32_t n_groups) {
  Store* s = (Store*)h;
  if (s->opt.kind == OPT_NONE) return -1;
  const int64_t n = n_groups > 0 ? key_ofs[n_groups] : 0;
  if (n == 0) return 0;
  thread_local std::vector<int32_t> row_group;
  if ((int64_t)row_group.size() < n) row_group.resize(n);
  for (int32_t g = 0; g < n_groups; ++g)
    for (int64_t i = key_ofs[g]; i < key_ofs[g + 1]; ++i) row_group[i] = g;
  thread_local std::vector<uint32_t> entry_lens;
  entry_lens.resize(n_groups);
  std::vector<std::pair<double, double>> bs(n_groups);
  for (int32_t g = 0; g < n_groups; ++g) {
    entry_lens[g] = dims[g] + s->opt.state_dim(dims[g]);
    bs[g] = s->get_batch_state(opt_groups[g]);
  }

  walk_rows_by_shard(
      s, signs, n,
      [&](Shard& sh, int64_t i, int32_t e) {
        const int32_t g = row_group[i];
        if (e < 0 || sh.entries[e].dim != dims[g] ||
            sh.entries[e].len != entry_lens[g])
          return RowAction::kDone;  // evicted / never admitted → skip
        prefetch_row(sh.entries[e].data, entry_lens[g]);
        return RowAction::kDefer;
      },
      [&](Shard& sh, int64_t i, int32_t e) {
        const int32_t g = row_group[i];
        const uint32_t dim = dims[g];
        sh.touch(e);
        float* data = sh.entries[e].data;
        s->update_entry(data, data + dim,
                        grads + grad_ofs[g] + (size_t)(i - key_ofs[g]) * dim,
                        dim, bs[g]);
      },
      [](Shard&, int64_t) {});  // update never mutates structure
  return 0;
}

// grads: (n, dim) row-major
int ps_update_gradients(void* h, const uint64_t* signs, int64_t n, uint32_t dim,
                        const float* grads, int group) {
  const int64_t key_ofs[2] = {0, n};
  const int64_t grad_ofs[1] = {0};
  return ps_update_batched(h, signs, key_ofs, &dim, grads, grad_ofs, &group, 1);
}

// values: (n, entry_len) full entries [emb | state]; dim = embedding dim
void ps_set_embedding(void* h, const uint64_t* signs, int64_t n, uint32_t dim,
                      uint32_t entry_len, const float* values) {
  Store* s = (Store*)h;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t sign = signs[i];
    Shard& sh = s->shard_of(sign);
    std::lock_guard<std::mutex> g(sh.mu);
    size_t pos = sh.find_pos(sign);
    if (pos != SIZE_MAX) sh.remove_entry(sh.table_slot[pos]);
    int32_t e = sh.insert(sign, dim, entry_len);
    std::memcpy(sh.entries[e].data, values + (size_t)i * entry_len,
                sizeof(float) * entry_len);
  }
}

// returns entry length, or -1 if absent; copies min(len, cap) floats into out
int32_t ps_get_entry(void* h, uint64_t sign, float* out, int32_t cap) {
  Store* s = (Store*)h;
  Shard& sh = s->shard_of(sign);
  std::lock_guard<std::mutex> g(sh.mu);
  size_t pos = sh.find_pos(sign);
  if (pos == SIZE_MAX) return -1;
  const Entry& en = sh.entries[sh.table_slot[pos]];
  int32_t ncopy = (int32_t)en.len < cap ? (int32_t)en.len : cap;
  if (out && ncopy > 0) std::memcpy(out, en.data, sizeof(float) * ncopy);
  return (int32_t)en.len;
}

// returns the entry's embedding dim, or -1 if absent
int32_t ps_get_entry_dim(void* h, uint64_t sign) {
  Store* s = (Store*)h;
  Shard& sh = s->shard_of(sign);
  std::lock_guard<std::mutex> g(sh.mu);
  size_t pos = sh.find_pos(sign);
  if (pos == SIZE_MAX) return -1;
  return (int32_t)sh.entries[sh.table_slot[pos]].dim;
}

int64_t ps_size(void* h) {
  Store* s = (Store*)h;
  int64_t total = 0;
  for (auto& sh : s->shards) {
    std::lock_guard<std::mutex> g(sh.mu);
    total += (int64_t)sh.count;
  }
  return total;
}

void ps_clear(void* h) {
  Store* s = (Store*)h;
  for (auto& sh : s->shards) {
    std::lock_guard<std::mutex> g(sh.mu);
    for (auto& en : sh.entries)
      if (en.data) {
        std::free(en.data);
        en.data = nullptr;
      }
    sh.entries.clear();
    sh.free_list.clear();
    std::fill(sh.table_slot.begin(), sh.table_slot.end(), -1);
    sh.lru_head = sh.lru_tail = -1;
    sh.count = 0;
  }
  std::lock_guard<std::mutex> g(s->batch_mu);
  s->batch_state.clear();
}

// Checkpoint wire format shared with the Python store:
//   u32 entry_count, then per entry: u64 sign, u32 dim, u32 len, len * f32.
// Entries are emitted in LRU order from least- to most-recently-used so that a
// dump→load roundtrip preserves relative recency.
int64_t ps_dump_shard_size(void* h, uint32_t shard) {
  Store* s = (Store*)h;
  if (shard >= s->num_shards) return -1;
  Shard& sh = s->shards[shard];
  std::lock_guard<std::mutex> g(sh.mu);
  int64_t bytes = 4;
  for (int32_t e = sh.lru_tail; e >= 0; e = sh.entries[e].prev)
    bytes += 16 + (int64_t)sh.entries[e].len * 4;
  return bytes;
}

int64_t ps_dump_shard(void* h, uint32_t shard, uint8_t* out, int64_t cap) {
  Store* s = (Store*)h;
  if (shard >= s->num_shards) return -1;
  Shard& sh = s->shards[shard];
  std::lock_guard<std::mutex> g(sh.mu);
  uint8_t* p = out;
  uint8_t* end = out + cap;
  if (p + 4 > end) return -1;
  uint32_t cnt = (uint32_t)sh.count;
  std::memcpy(p, &cnt, 4);
  p += 4;
  for (int32_t e = sh.lru_tail; e >= 0; e = sh.entries[e].prev) {
    const Entry& en = sh.entries[e];
    int64_t need = 16 + (int64_t)en.len * 4;
    if (p + need > end) return -1;
    std::memcpy(p, &en.sign, 8);
    std::memcpy(p + 8, &en.dim, 4);
    std::memcpy(p + 12, &en.len, 4);
    std::memcpy(p + 16, en.data, (size_t)en.len * 4);
    p += need;
  }
  return p - out;
}

// ------------------------------------------------------------ apply-journal
// Trainer-resume exactly-once hooks: record/probe applied gradient-batch
// ids (see Store::journal_*). Journal state is intentionally NOT part of
// the shard dump wire format — a PS rewind (clear + shard replay) must
// also clear the journal so replayed post-fence batches re-apply.

void ps_journal_record(void* h, uint64_t id, uint32_t crc) {
  ((Store*)h)->journal_record(id, crc);
}

// 1 = already applied (crc matches), 0 = unknown id, -1 = crc mismatch
int32_t ps_journal_probe(void* h, uint64_t id, uint32_t crc) {
  return ((Store*)h)->journal_probe(id, crc);
}

int64_t ps_journal_len(void* h) {
  Store* s = (Store*)h;
  std::lock_guard<std::mutex> g(s->journal_mu);
  return (int64_t)s->journal_map.size();
}

void ps_journal_clear(void* h) { ((Store*)h)->journal_clear(); }

int64_t ps_load_shard(void* h, const uint8_t* data, int64_t len) {
  Store* s = (Store*)h;
  if (len < 4) return -1;
  uint32_t cnt;
  std::memcpy(&cnt, data, 4);
  const uint8_t* p = data + 4;
  const uint8_t* end = data + len;
  for (uint32_t i = 0; i < cnt; ++i) {
    if (p + 16 > end) return -1;
    uint64_t sign;
    uint32_t edim, elen;
    std::memcpy(&sign, p, 8);
    std::memcpy(&edim, p + 8, 4);
    std::memcpy(&elen, p + 12, 4);
    p += 16;
    if (p + (int64_t)elen * 4 > end) return -1;
    Shard& sh = s->shard_of(sign);
    {
      std::lock_guard<std::mutex> g(sh.mu);
      size_t pos = sh.find_pos(sign);
      if (pos != SIZE_MAX) sh.remove_entry(sh.table_slot[pos]);
      int32_t e = sh.insert(sign, edim, elen);
      std::memcpy(sh.entries[e].data, p, (size_t)elen * 4);
    }
    p += (int64_t)elen * 4;
  }
  return (int64_t)cnt;
}

// ------------------------------------------------------- elastic handoff
// Range export/import/delete for live PS resharding: an entry belongs to
// the hash range [lo, hi) iff splitmix64(sign) — the ROUTING hash the
// worker's ring positions on, NOT the store-internal `sign ^ 0xA5A5A5A5`
// shard hash — lies in it; hi == 0 encodes 2^64 (the end of the ring),
// which a u64 cannot carry. Export emits the dump_shard wire format but
// SORTED BY SIGN (dump_shard is LRU-ordered): a re-export after any crash
// or restore yields byte-identical payload, so the handoff journal's crc
// dedups replays. Import is plain ps_load_shard (sign-routed, any layout).

static inline bool range_owns(uint64_t sign, uint64_t lo, uint64_t hi) {
  uint64_t hh = splitmix64(sign);
  return hh >= lo && (hi == 0 || hh < hi);
}

int64_t ps_export_range_size(void* h, uint64_t lo, uint64_t hi) {
  Store* s = (Store*)h;
  int64_t bytes = 4;
  for (auto& sh : s->shards) {
    std::lock_guard<std::mutex> g(sh.mu);
    for (const Entry& en : sh.entries) {
      if (!en.data) continue;  // free-listed slot
      if (range_owns(en.sign, lo, hi)) bytes += 16 + (int64_t)en.len * 4;
    }
  }
  return bytes;
}

int64_t ps_export_range(void* h, uint64_t lo, uint64_t hi, uint8_t* out,
                        int64_t cap) {
  Store* s = (Store*)h;
  // copy matching entries out under per-shard locks, then sort by sign and
  // serialize lock-free — the extra copy buys deterministic bytes (handoff
  // is a fence-time path, not a hot one)
  struct Row {
    uint64_t sign;
    uint32_t dim, len;
    std::vector<float> data;
  };
  std::vector<Row> rows;
  for (auto& sh : s->shards) {
    std::lock_guard<std::mutex> g(sh.mu);
    for (const Entry& en : sh.entries) {
      if (!en.data || !range_owns(en.sign, lo, hi)) continue;
      rows.push_back(Row{en.sign, en.dim, en.len,
                         std::vector<float>(en.data, en.data + en.len)});
    }
  }
  std::sort(rows.begin(), rows.end(),
            [](const Row& a, const Row& b) { return a.sign < b.sign; });
  uint8_t* p = out;
  uint8_t* end = out + cap;
  if (p + 4 > end) return -1;
  uint32_t cnt = (uint32_t)rows.size();
  std::memcpy(p, &cnt, 4);
  p += 4;
  for (const Row& r : rows) {
    int64_t need = 16 + (int64_t)r.len * 4;
    if (p + need > end) return -1;
    std::memcpy(p, &r.sign, 8);
    std::memcpy(p + 8, &r.dim, 4);
    std::memcpy(p + 12, &r.len, 4);
    std::memcpy(p + 16, r.data.data(), (size_t)r.len * 4);
    p += need;
  }
  return p - out;
}

// drop every entry in [lo, hi); returns entries removed (idempotent — a
// journal-deduped replay of the delete removes 0)
int64_t ps_delete_range(void* h, uint64_t lo, uint64_t hi) {
  Store* s = (Store*)h;
  int64_t removed = 0;
  for (auto& sh : s->shards) {
    std::lock_guard<std::mutex> g(sh.mu);
    std::vector<int32_t> victims;
    for (int32_t e = 0; e < (int32_t)sh.entries.size(); ++e) {
      const Entry& en = sh.entries[e];
      if (en.data && range_owns(en.sign, lo, hi)) victims.push_back(e);
    }
    for (int32_t e : victims) sh.remove_entry(e);
    removed += (int64_t)victims.size();
  }
  return removed;
}

// Fence-point row scrubber (persia_tpu/health): scan every live entry for
// NaN/Inf anywhere in its [emb | state] floats and repair damaged rows to
// the deterministic seeded init — the SAME contract as a degraded-mode or
// cold lookup (init_embedding + init_state), so a scrubbed row is
// indistinguishable from a freshly admitted one. Returns the repaired-row
// count; up to `cap` repaired signs land in out_signs for the caller's
// journal / flight-recorder record. Per-shard locking only — lookups on
// other shards proceed during the scan.
int64_t ps_scan_nonfinite(void* h, uint64_t* out_signs, int64_t cap) {
  Store* s = (Store*)h;
  int64_t repaired = 0;
  for (uint32_t si = 0; si < s->num_shards; ++si) {
    Shard& sh = s->shards[si];
    std::lock_guard<std::mutex> g(sh.mu);
    for (Entry& en : sh.entries) {
      if (!en.data) continue;  // free-listed slot
      bool bad = false;
      for (uint32_t i = 0; i < en.len; ++i) {
        if (!std::isfinite(en.data[i])) { bad = true; break; }
      }
      if (!bad) continue;
      s->init_embedding(en.sign, en.dim, en.data);
      s->init_state(en.dim, en.data + en.dim);
      if (repaired < cap) out_signs[repaired] = en.sign;
      ++repaired;
    }
  }
  return repaired;
}

}  // extern "C"
